"""Operator dispatch: the TPU analog of the imperative invoke path.

Reference call stack (SURVEY.md §3.1): Python op → FFI → ``Imperative::Invoke``
→ shape/type inference → ``PushFCompute`` closure → engine → kernel.

TPU call stack: Python op → :func:`apply` → (optionally ``jax.vjp`` for
autograd) → XLA async dispatch. Shape/dtype inference, memory planning and
kernel selection are XLA's job; what remains here is (a) unwrap/wrap of the
mutable NDArray handles, (b) tape recording, (c) the NaiveEngine sync hook.

Ops are plain JAX-traceable functions. :func:`register` places them in a
global table by name — the analog of ``NNVM_REGISTER_OP`` — which the
``mx.np``/``mx.npx``/``mx.nd`` namespace generators read at import, the way
the reference synthesizes its Python op modules from the C registry
(``python/mxnet/ndarray/register.py:115-265``).
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Optional

from .. import autograd, engine
from ..base import MXNetError

# global op table: name -> Op
_OPS: Dict[str, "Op"] = {}

# telemetry hot-state (mxnet_tpu.profiler.core), installed by the first
# profiler.set_state('run') and never imported on the dispatch path: a
# session that never profiles pays exactly one `is None` test per apply()
_PROF = None

# fault-injection hot-state (mxnet_tpu.resilience.faults.FaultPlan),
# installed by faults.install_plan() the same way: one `is None` test per
# apply() when no plan is active
_FAULTS = None

# ---------------------------------------------------------------------------
# Eager per-op jit cache (SURVEY.md §7 hard part 2)
#
# The reference keeps eager dispatch cheap by caching shape/dtype inference
# per op signature (`SetShapeType`, `src/imperative/imperative.cc:117`). The
# TPU analog: cache a `jax.jit` of the op callable keyed on everything
# static — the function's code + closure values, non-array args, kwargs —
# and let jit's own signature cache handle shapes/dtypes. One compiled
# executable per (op, static config) replaces a fresh trace through op
# Python + per-primitive dispatch on every imperative call.
# ---------------------------------------------------------------------------

_EAGER_JIT_CACHE: Dict[tuple, Callable] = {}
_EAGER_BWD_CACHE: Dict[tuple, Callable] = {}  # same keys: compiled vjp
_EAGER_JIT_SKIP = set()  # keys whose trace consumed RNG: never cache
_KEPT_CALLABLES: Dict[int, Callable] = {}  # id-keyed pins (see _static_key)
_EAGER_JIT_MAX = 4096  # runaway guard: clear rather than evict
_eager_jit_enabled = os.environ.get("MXNET_EAGER_JIT_CACHE", "1") != "0"


def set_eager_jit(flag: bool) -> None:
    """Enable/disable the eager per-op jit cache (MXNET_EAGER_JIT_CACHE)."""
    global _eager_jit_enabled
    _eager_jit_enabled = bool(flag)


def eager_jit_cache_size() -> int:
    return len(_EAGER_JIT_CACHE)


def _static_key(v, depth=0):
    """Hashable identity of a static value; TypeError means 'don't cache'.

    Functions key on (code object, closure values) so the per-call inner
    closures in ops/nn.py (same code, different stride/pad cells) cache
    correctly instead of colliding or leaking.
    """
    if depth > 6:
        raise TypeError("static key too deep")
    if v is None or isinstance(v, (str, bytes, type)):
        return v
    if isinstance(v, (bool, int, float, complex)):
        # type-tagged: True==1==1.0 and 0.0==-0.0 hash-collide, but pick
        # different weak-type/sign behavior under jax — must not share a key
        return (type(v).__name__, repr(v))
    if isinstance(v, (tuple, list)):
        return (type(v).__name__,) + tuple(
            _static_key(x, depth + 1) for x in v)
    if isinstance(v, dict):
        return tuple(sorted(
            (k, _static_key(x, depth + 1)) for k, x in v.items()))
    import types

    if isinstance(v, types.ModuleType):
        return ("module", v.__name__)
    if isinstance(v, types.MethodType):
        # bound method: the receiver is part of the identity — two
        # instances sharing a class must not share a cache entry
        return ("method", v.__func__.__code__,
                _static_key(v.__self__, depth + 1))
    if callable(v) and hasattr(v, "__code__"):
        return (v.__code__,) + tuple(
            _static_key(c.cell_contents, depth + 1)
            for c in (v.__closure__ or ()))
    if callable(v):
        # opaque long-lived callables (jnp ufunc / PjitFunction objects):
        # key by identity, pinning a reference so the id is never reused
        _KEPT_CALLABLES.setdefault(id(v), v)
        return ("callable", type(v).__name__, id(v))
    import numpy as _onp

    if isinstance(v, _onp.dtype) or (isinstance(v, type(_onp.float32))):
        return str(v)
    if isinstance(v, _onp.ndarray) or hasattr(v, "__jax_array__") or \
            hasattr(v, "_data"):
        raise TypeError(f"array-valued static arg {type(v).__name__}")
    try:
        hash(v)
    except TypeError:
        raise TypeError(
            f"unhashable static arg {type(v).__name__}") from None
    # value-hashable objects (PyTreeDef, dtypes, enums) key directly; the
    # cache tuple keeps `v` alive, so id-hashed objects can't be recycled
    # into false hits
    return v


class Op:
    """A registered operator.

    ``wrapper=False`` (default): ``fn`` is a raw JAX-traceable callable and
    calls dispatch through :func:`apply`. ``wrapper=True``: ``fn`` is a
    public NDArray-level function that does its own dispatch (the ops in
    ``ops/nn.py``) and is invoked directly — routing it through ``apply``
    again would nest dispatch and leak NDArrays into jax.vjp.
    """

    __slots__ = ("name", "fn", "wrapper", "doc")

    def __init__(self, name: str, fn: Callable, wrapper=False, doc=""):
        self.name = name
        self.fn = fn
        self.wrapper = wrapper
        self.doc = doc or fn.__doc__

    def __call__(self, *args, **kwargs):
        if self.wrapper:
            return self.fn(*args, **kwargs)
        return apply(self.fn, args, kwargs, name=self.name)


def register(name: str, fn: Optional[Callable] = None, **meta):
    """Register an op (decorator or direct). Analog of NNVM_REGISTER_OP."""
    if fn is None:
        def deco(f):
            _OPS[name] = Op(name, f, **meta)
            return f
        return deco
    _OPS[name] = Op(name, fn, **meta)
    return fn


def get(name: str) -> Op:
    try:
        return _OPS[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered") from None


def list_ops():
    """All registered op names (``MXListAllOpNames`` analog)."""
    return sorted(_OPS)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------


def _ndarray_cls():
    from ..ndarray.ndarray import NDArray

    return NDArray


def _make_cached_vjp(inner_fn, datas, key):
    """Tape-node backward as ONE compiled executable per op key.

    The naive eager tape stores the closure ``jax.vjp`` returns and calls
    it at backward time — which interprets the transposed jaxpr in Python,
    primitive by primitive, every step (measured ~120 ms of a ~145 ms
    eager LeNet step). Here backward is ``jit(cts, xs -> vjp(f, xs)(cts))``
    cached under the SAME static key as the forward executable:
    recompute-in-backward (the forward re-runs inside the compiled vjp, a
    remat the compiler fuses) in exchange for zero per-step retracing and
    no Python-held residuals.
    """

    def vjp_fn(cts):
        import jax

        bwd = _EAGER_BWD_CACHE.get(key)
        if bwd is None:
            def bwd_fn(cts_, xs):
                _, vjp = jax.vjp(inner_fn, *xs)
                out = vjp(cts_)
                # int/bool inputs get float0 cotangents, which jit cannot
                # return — drop them to None leaves (ignored by the walk)
                return tuple(
                    None if (hasattr(c, "dtype")
                             and c.dtype == jax.dtypes.float0) else c
                    for c in out)

            bwd = jax.jit(bwd_fn)
            _EAGER_BWD_CACHE[key] = bwd
        return bwd(cts, datas)

    return vjp_fn


def apply(fn, args, kwargs=None, name="", record=True, sync_outputs=True,
          static_key=None, cacheable=True):
    """Invoke ``fn`` on a mix of NDArray / scalar / array args.

    NDArray positions become differentiable primal inputs; everything else is
    closed over as a constant. When autograd is recording and any NDArray
    input is tracked, forward runs under ``jax.vjp`` and a tape node is
    created (``Imperative::RecordOp`` analog).

    ``static_key`` — optional precomputed hashable identity of everything
    static about this call (op + config). When given, the eager jit cache
    uses it directly instead of walking ``fn``'s closure, which keeps the
    per-call overhead down on hot namespace ops.
    """
    import jax

    prof = _PROF
    if prof is not None and prof.IMPERATIVE:
        # opt-in per-op call counters (profile_imperative): the role of the
        # reference's imperative API events, without the always-on cost
        prof.count_op(name or getattr(fn, "__name__", "op"))

    flt = _FAULTS
    if flt is not None:
        # injected transient dispatch error (resilience.faults): raised
        # BEFORE any tape/cache mutation so a caller-level retry sees a
        # clean slate. No info payload — building one per dispatch would
        # cost more than the site check itself
        flt.check("op:dispatch")

    NDArray = _ndarray_cls()
    kwargs = kwargs or {}
    arr_pos = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    arrays = [args[i] for i in arr_pos]
    datas = tuple(a._data for a in arrays)

    if arr_pos and len(arr_pos) == len(args) and not kwargs:
        closed = fn
    else:
        template = list(args)

        def closed(*xs):
            for pos, x in zip(arr_pos, xs):
                template[pos] = x
            return fn(*template, **kwargs)

    cache_key = None
    cache_candidate = None
    rng_mark = 0
    jit_hit_key = None  # verified-cacheable op: fast fwd AND cached-vjp bwd
    if _eager_jit_enabled and cacheable:
        try:
            if static_key is not None:
                key = static_key
            else:
                pos_set = set(arr_pos)
                key = (
                    _static_key(fn),
                    tuple(arr_pos),
                    len(args),
                    tuple(_static_key(a) for i, a in enumerate(args)
                          if i not in pos_set),
                    _static_key(kwargs),
                )
            if key not in _EAGER_JIT_SKIP:
                jitted = _EAGER_JIT_CACHE.get(key)
                if jitted is not None:
                    closed = jitted
                    jit_hit_key = key
                else:
                    from .. import random as _rng

                    # jit now, publish to the cache only after the call
                    # traced without drawing an RNG key (a cached trace
                    # would replay the same baked key forever)
                    rng_mark = _rng.consume_count()
                    cache_key = key
                    _uncached_closed = closed
                    cache_candidate = jax.jit(closed)
                    closed = cache_candidate
        except TypeError:
            pass  # unhashable static config (e.g. array-valued kwargs)

    from ..ndarray.ndarray import _tracked, _slot_of

    recording = (
        record
        and autograd.is_recording()
        and any(_tracked(a) for a in arrays)
    )
    was_list = False

    def normalized(*xs):
        # multi-output ops (split, qr, slogdet...) may return lists or
        # namedtuples; the tape's cotangent convention is plain tuples, so
        # normalize at the vjp boundary (remembering listness so the caller
        # sees the same container type with or without recording)
        nonlocal was_list
        r = closed(*xs)
        if isinstance(r, list):
            was_list = True
            return tuple(r)
        if isinstance(r, tuple) and hasattr(r, "_fields"):
            return tuple(r)
        return r

    try:
        if recording and jit_hit_key is not None:
            # verified-cacheable op (cache hit => its trace is RNG-free and
            # jit-compatible): run the compiled forward directly — no
            # per-call jax.vjp retrace — and defer backward to the cached
            # compiled vjp. First encounters and RNG ops keep the eager
            # jax.vjp path (an RNG op's backward replay would re-draw keys
            # and mismatch the forward's masks).
            outs = normalized(*datas)
            vjp_fn = _make_cached_vjp(normalized, datas, jit_hit_key)
        elif recording:
            outs, vjp_fn = jax.vjp(normalized, *datas)
        else:
            outs = normalized(*datas)
    except Exception:
        if cache_candidate is None:
            raise
        # maybe jit-specific (value-dependent Python: dynamic output
        # shapes, host reads) — retry eagerly; only a SUCCESSFUL retry
        # proves jit-incompatibility and justifies skipping the cache
        # forever (a plain user error must not poison the key)
        closed = _uncached_closed
        cache_candidate = None
        if recording:
            outs, vjp_fn = jax.vjp(normalized, *datas)
        else:
            outs = normalized(*datas)
        _EAGER_JIT_SKIP.add(cache_key)

    if cache_candidate is not None:
        from .. import random as _rng

        if _rng.consume_count() == rng_mark:
            if len(_EAGER_JIT_CACHE) >= _EAGER_JIT_MAX:
                _EAGER_JIT_CACHE.clear()
                _EAGER_BWD_CACHE.clear()
            _EAGER_JIT_CACHE[cache_key] = cache_candidate
        else:
            _EAGER_JIT_SKIP.add(cache_key)

    single = not isinstance(outs, (tuple, list))
    flat = [outs] if single else list(outs)
    wrapped = [NDArray(o) for o in flat]

    if recording:
        if not single and len(flat) == 1:
            # the tape walk hands a bare leaf when there's one output, but
            # jax.vjp of a 1-tuple-returning fn wants a 1-tuple cotangent
            raw_vjp = vjp_fn
            vjp_fn = (lambda ct, _raw=raw_vjp:
                      _raw(ct if isinstance(ct, tuple) else (ct,)))
        node = autograd.TapeNode(
            vjp_fn,
            [_slot_of(a) for a in arrays],
            [(o.shape, o.dtype) for o in flat],
            name=name or getattr(fn, "__name__", "op"),
            # saved for create_graph=True: the backward walk re-linearizes
            # this op as a recorded op (higher-order autograd)
            fwd_fn=normalized,
            in_arrays=list(arrays),
        )
        # create_graph's replay must hand jax.vjp a cotangent matching the
        # forward's output structure: bare leaf vs 1-tuple
        node.out_container = not single
        for i, w in enumerate(wrapped):
            w._tape = (node, i)

    if sync_outputs:
        engine.maybe_sync(flat)
    if single:
        return wrapped[0]
    return list(wrapped) if was_list else type(outs)(wrapped)


def apply_out(fn, args, kwargs=None, out=None, name=""):
    """Like :func:`apply` but honoring an ``out=`` destination NDArray."""
    res = apply(fn, args, kwargs, name=name)
    if out is None:
        return res
    if isinstance(out, (tuple, list)):
        for o, r in zip(out, res):
            o._set_data_internal(r._data)
        return out
    out._set_data_internal(res._data)
    out._tape = getattr(res, "_tape", None)
    return out
