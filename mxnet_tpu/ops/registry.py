"""Operator dispatch: the TPU analog of the imperative invoke path.

Reference call stack (SURVEY.md §3.1): Python op → FFI → ``Imperative::Invoke``
→ shape/type inference → ``PushFCompute`` closure → engine → kernel.

TPU call stack: Python op → :func:`apply` → (optionally ``jax.vjp`` for
autograd) → XLA async dispatch. Shape/dtype inference, memory planning and
kernel selection are XLA's job; what remains here is (a) unwrap/wrap of the
mutable NDArray handles, (b) tape recording, (c) the NaiveEngine sync hook.

Ops are plain JAX-traceable functions. :func:`register` places them in a
global table by name — the analog of ``NNVM_REGISTER_OP`` — which the
``mx.np``/``mx.npx``/``mx.nd`` namespace generators read at import, the way
the reference synthesizes its Python op modules from the C registry
(``python/mxnet/ndarray/register.py:115-265``).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from .. import autograd, engine
from ..base import MXNetError

# global op table: name -> Op
_OPS: Dict[str, "Op"] = {}


class Op:
    """A registered operator.

    ``wrapper=False`` (default): ``fn`` is a raw JAX-traceable callable and
    calls dispatch through :func:`apply`. ``wrapper=True``: ``fn`` is a
    public NDArray-level function that does its own dispatch (the ops in
    ``ops/nn.py``) and is invoked directly — routing it through ``apply``
    again would nest dispatch and leak NDArrays into jax.vjp.
    """

    __slots__ = ("name", "fn", "wrapper", "doc")

    def __init__(self, name: str, fn: Callable, wrapper=False, doc=""):
        self.name = name
        self.fn = fn
        self.wrapper = wrapper
        self.doc = doc or fn.__doc__

    def __call__(self, *args, **kwargs):
        if self.wrapper:
            return self.fn(*args, **kwargs)
        return apply(self.fn, args, kwargs, name=self.name)


def register(name: str, fn: Optional[Callable] = None, **meta):
    """Register an op (decorator or direct). Analog of NNVM_REGISTER_OP."""
    if fn is None:
        def deco(f):
            _OPS[name] = Op(name, f, **meta)
            return f
        return deco
    _OPS[name] = Op(name, fn, **meta)
    return fn


def get(name: str) -> Op:
    try:
        return _OPS[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered") from None


def list_ops():
    """All registered op names (``MXListAllOpNames`` analog)."""
    return sorted(_OPS)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------


def _ndarray_cls():
    from ..ndarray.ndarray import NDArray

    return NDArray


def apply(fn, args, kwargs=None, name="", record=True, sync_outputs=True):
    """Invoke ``fn`` on a mix of NDArray / scalar / array args.

    NDArray positions become differentiable primal inputs; everything else is
    closed over as a constant. When autograd is recording and any NDArray
    input is tracked, forward runs under ``jax.vjp`` and a tape node is
    created (``Imperative::RecordOp`` analog).
    """
    import jax

    NDArray = _ndarray_cls()
    kwargs = kwargs or {}
    arr_pos = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    arrays = [args[i] for i in arr_pos]
    datas = tuple(a._data for a in arrays)

    if arr_pos and len(arr_pos) == len(args) and not kwargs:
        closed = fn
    else:
        template = list(args)

        def closed(*xs):
            for pos, x in zip(arr_pos, xs):
                template[pos] = x
            return fn(*template, **kwargs)

    from ..ndarray.ndarray import _tracked, _slot_of

    recording = (
        record
        and autograd.is_recording()
        and any(_tracked(a) for a in arrays)
    )
    was_list = False

    def normalized(*xs):
        # multi-output ops (split, qr, slogdet...) may return lists or
        # namedtuples; the tape's cotangent convention is plain tuples, so
        # normalize at the vjp boundary (remembering listness so the caller
        # sees the same container type with or without recording)
        nonlocal was_list
        r = closed(*xs)
        if isinstance(r, list):
            was_list = True
            return tuple(r)
        if isinstance(r, tuple) and hasattr(r, "_fields"):
            return tuple(r)
        return r

    if recording:
        outs, vjp_fn = jax.vjp(normalized, *datas)
    else:
        outs = normalized(*datas)

    single = not isinstance(outs, (tuple, list))
    flat = [outs] if single else list(outs)
    wrapped = [NDArray(o) for o in flat]

    if recording:
        node = autograd.TapeNode(
            vjp_fn,
            [_slot_of(a) for a in arrays],
            [(o.shape, o.dtype) for o in flat],
            name=name or getattr(fn, "__name__", "op"),
        )
        for i, w in enumerate(wrapped):
            w._tape = (node, i)

    if sync_outputs:
        engine.maybe_sync(flat)
    if single:
        return wrapped[0]
    return list(wrapped) if was_list else type(outs)(wrapped)


def apply_out(fn, args, kwargs=None, out=None, name=""):
    """Like :func:`apply` but honoring an ``out=`` destination NDArray."""
    res = apply(fn, args, kwargs, name=name)
    if out is None:
        return res
    if isinstance(out, (tuple, list)):
        for o, r in zip(out, res):
            o._set_data_internal(r._data)
        return out
    out._set_data_internal(res._data)
    out._tape = getattr(res, "_tape", None)
    return out
