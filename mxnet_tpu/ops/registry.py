"""Operator dispatch: the TPU analog of the imperative invoke path.

Reference call stack (SURVEY.md §3.1): Python op → FFI → ``Imperative::Invoke``
→ shape/type inference → ``PushFCompute`` closure → engine → kernel.

TPU call stack: Python op → :func:`apply` → (optionally ``jax.vjp`` for
autograd) → XLA async dispatch. Shape/dtype inference, memory planning and
kernel selection are XLA's job; what remains here is (a) unwrap/wrap of the
mutable NDArray handles, (b) tape recording, (c) the NaiveEngine sync hook.

Ops are plain JAX-traceable functions. :func:`register` places them in a
global table by name — the analog of ``NNVM_REGISTER_OP`` — which the
``mx.np``/``mx.npx``/``mx.nd`` namespace generators read at import, the way
the reference synthesizes its Python op modules from the C registry
(``python/mxnet/ndarray/register.py:115-265``).
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Optional

from .. import autograd, engine
from ..base import MXNetError

# global op table: name -> Op
_OPS: Dict[str, "Op"] = {}

# telemetry hot-state (mxnet_tpu.profiler.core), installed by the first
# profiler.set_state('run') and never imported on the dispatch path: a
# session that never profiles pays exactly one `is None` test per apply()
_PROF = None

# fault-injection hot-state (mxnet_tpu.resilience.faults.FaultPlan),
# installed by faults.install_plan() the same way: one `is None` test per
# apply() when no plan is active
_FAULTS = None

# ---------------------------------------------------------------------------
# Eager per-op jit cache (SURVEY.md §7 hard part 2)
#
# The reference keeps eager dispatch cheap by caching shape/dtype inference
# per op signature (`SetShapeType`, `src/imperative/imperative.cc:117`). The
# TPU analog: cache a `jax.jit` of the op callable keyed on everything
# static — the function's code + closure values, non-array args, kwargs —
# and let jit's own signature cache handle shapes/dtypes. One compiled
# executable per (op, static config) replaces a fresh trace through op
# Python + per-primitive dispatch on every imperative call.
# ---------------------------------------------------------------------------

_EAGER_JIT_CACHE: Dict[tuple, Callable] = {}
_EAGER_BWD_CACHE: Dict[tuple, Callable] = {}  # same keys: compiled vjp
_EAGER_JIT_SKIP = set()  # keys whose trace consumed RNG: never cache
_KEPT_CALLABLES: Dict[int, Callable] = {}  # id-keyed pins (see _static_key)
_EAGER_JIT_MAX = 4096  # runaway guard: clear rather than evict
_EAGER_JIT_CLEARS = 0  # how often the runaway guard wiped the cache
_eager_jit_enabled = os.environ.get("MXNET_EAGER_JIT_CACHE", "1") != "0"

# deferred-dispatch aval cache: (op key, input avals) -> either
# ("ok", flat_avals, single, was_list) or ("nodefer",) for ops whose
# abstract trace consumed RNG or failed (value-dependent Python) — those
# always take the direct dispatch path.  Bounded by _EAGER_JIT_MAX with
# the same clear-don't-evict discipline.
_AVAL_CACHE: Dict[tuple, tuple] = {}
_AVAL_CLEARS = 0  # runaway-guard wipes of the aval cache (cache_stats)


def set_eager_jit(flag: bool) -> None:
    """Enable/disable the eager per-op jit cache (MXNET_EAGER_JIT_CACHE)."""
    global _eager_jit_enabled
    _eager_jit_enabled = bool(flag)


def eager_jit_cache_size() -> int:
    return len(_EAGER_JIT_CACHE)


def cache_stats():
    """Eager jit-cache telemetry (the registry analog of
    ``CachedOp.cache_stats()``): entry counts, RNG-skip count, and how
    often the runaway guard cleared everything — a nonzero ``clears``
    rate in a steady-state loop means static keys are churning (cache
    thrash) and the clear is silently re-paying compile cost."""
    return {"size": len(_EAGER_JIT_CACHE),
            "bwd_size": len(_EAGER_BWD_CACHE),
            "skips": len(_EAGER_JIT_SKIP),
            "clears": _EAGER_JIT_CLEARS,
            "aval_size": len(_AVAL_CACHE),
            "aval_clears": _AVAL_CLEARS,
            "limit": _EAGER_JIT_MAX}


def _note_cache_clear(what="eager jit cache", counter="eager_jit_clears",
                      count=1, limit=None):
    """Account (and rate-limitedly warn about) a runaway-guard cache
    clear — previously silent, so cache-thrash regressions in BENCH
    rounds were unattributable. Shared by the per-op jit cache and the
    deferred-dispatch aval cache; returns the new clear count."""
    prof = _PROF
    if prof is not None:
        prof.set_counter(f"registry.{counter}", count, cat="registry")
    if count == 1 or count % 10 == 0:
        import warnings

        warnings.warn(
            f"{what} hit its {limit or _EAGER_JIT_MAX}-entry runaway "
            f"guard and was cleared (clear #{count}); something is "
            f"generating unbounded distinct op signatures (varying "
            f"shapes/static args) and re-paying compiles — see "
            f"registry.cache_stats()", RuntimeWarning, stacklevel=3)
    return count


def _static_key(v, depth=0):
    """Hashable identity of a static value; TypeError means 'don't cache'.

    Functions key on (code object, closure values) so the per-call inner
    closures in ops/nn.py (same code, different stride/pad cells) cache
    correctly instead of colliding or leaking.
    """
    if depth > 6:
        raise TypeError("static key too deep")
    if v is None or isinstance(v, (str, bytes, type)):
        return v
    if isinstance(v, (bool, int, float, complex)):
        # type-tagged: True==1==1.0 and 0.0==-0.0 hash-collide, but pick
        # different weak-type/sign behavior under jax — must not share a key
        return (type(v).__name__, repr(v))
    if isinstance(v, (tuple, list)):
        return (type(v).__name__,) + tuple(
            _static_key(x, depth + 1) for x in v)
    if isinstance(v, slice):
        # unhashable before Python 3.12 — without this branch every basic
        # __getitem__/__setitem__ closure (jkey) is uncacheable AND
        # undeferrable, fragmenting bulk segments back to per-op dispatch
        return ("slice", _static_key(v.start, depth + 1),
                _static_key(v.stop, depth + 1),
                _static_key(v.step, depth + 1))
    if isinstance(v, dict):
        return tuple(sorted(
            (k, _static_key(x, depth + 1)) for k, x in v.items()))
    import types

    if isinstance(v, types.ModuleType):
        return ("module", v.__name__)
    if isinstance(v, types.MethodType):
        # bound method: the receiver is part of the identity — two
        # instances sharing a class must not share a cache entry
        return ("method", v.__func__.__code__,
                _static_key(v.__self__, depth + 1))
    if callable(v) and hasattr(v, "__code__"):
        return (v.__code__,) + tuple(
            _static_key(c.cell_contents, depth + 1)
            for c in (v.__closure__ or ()))
    if callable(v):
        # opaque long-lived callables (jnp ufunc / PjitFunction objects):
        # key by identity, pinning a reference so the id is never reused
        _KEPT_CALLABLES.setdefault(id(v), v)
        return ("callable", type(v).__name__, id(v))
    import numpy as _onp

    if isinstance(v, _onp.dtype) or (isinstance(v, type(_onp.float32))):
        return str(v)
    if isinstance(v, _onp.ndarray) or hasattr(v, "__jax_array__") or \
            hasattr(v, "_data"):
        raise TypeError(f"array-valued static arg {type(v).__name__}")
    try:
        hash(v)
    except TypeError:
        raise TypeError(
            f"unhashable static arg {type(v).__name__}") from None
    # value-hashable objects (PyTreeDef, dtypes, enums) key directly; the
    # cache tuple keeps `v` alive, so id-hashed objects can't be recycled
    # into false hits
    return v


class Op:
    """A registered operator.

    ``wrapper=False`` (default): ``fn`` is a raw JAX-traceable callable and
    calls dispatch through :func:`apply`. ``wrapper=True``: ``fn`` is a
    public NDArray-level function that does its own dispatch (the ops in
    ``ops/nn.py``) and is invoked directly — routing it through ``apply``
    again would nest dispatch and leak NDArrays into jax.vjp.
    """

    __slots__ = ("name", "fn", "wrapper", "doc")

    def __init__(self, name: str, fn: Callable, wrapper=False, doc=""):
        self.name = name
        self.fn = fn
        self.wrapper = wrapper
        self.doc = doc or fn.__doc__

    def __call__(self, *args, **kwargs):
        if self.wrapper:
            return self.fn(*args, **kwargs)
        return apply(self.fn, args, kwargs, name=self.name)


def register(name: str, fn: Optional[Callable] = None, **meta):
    """Register an op (decorator or direct). Analog of NNVM_REGISTER_OP."""
    if fn is None:
        def deco(f):
            _OPS[name] = Op(name, f, **meta)
            return f
        return deco
    _OPS[name] = Op(name, fn, **meta)
    return fn


def get(name: str) -> Op:
    try:
        return _OPS[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered") from None


def list_ops():
    """All registered op names (``MXListAllOpNames`` analog)."""
    return sorted(_OPS)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------


def _ndarray_cls():
    from ..ndarray.ndarray import NDArray

    return NDArray


def _make_cached_vjp(inner_fn, datas, key):
    """Tape-node backward as ONE compiled executable per op key.

    The naive eager tape stores the closure ``jax.vjp`` returns and calls
    it at backward time — which interprets the transposed jaxpr in Python,
    primitive by primitive, every step (measured ~120 ms of a ~145 ms
    eager LeNet step). Here backward is ``jit(cts, xs -> vjp(f, xs)(cts))``
    cached under the SAME static key as the forward executable:
    recompute-in-backward (the forward re-runs inside the compiled vjp, a
    remat the compiler fuses) in exchange for zero per-step retracing and
    no Python-held residuals.
    """

    def vjp_fn(cts):
        import jax

        bwd = _EAGER_BWD_CACHE.get(key)
        if bwd is None:
            def bwd_fn(cts_, xs):
                _, vjp = jax.vjp(inner_fn, *xs)
                out = vjp(cts_)
                # int/bool inputs get float0 cotangents, which jit cannot
                # return — drop them to None leaves (ignored by the walk)
                return tuple(
                    None if (hasattr(c, "dtype")
                             and c.dtype == jax.dtypes.float0) else c
                    for c in out)

            bwd = jax.jit(bwd_fn)
            _EAGER_BWD_CACHE[key] = bwd
        return bwd(cts, datas)

    return vjp_fn


_NOT_DEFERRED = object()  # sentinel: _maybe_defer declined, dispatch directly
_KEY_ERR = object()       # sentinel: static key is unhashable (TypeError)

_TRACER_CLS = None


def _jax_tracer():
    global _TRACER_CLS
    if _TRACER_CLS is None:
        import jax.core

        _TRACER_CLS = jax.core.Tracer
    return _TRACER_CLS


def _op_static_key(fn, args, kwargs, arr_pos, static_key):
    """The (op, static config) identity used by both the per-op jit cache
    and the deferred-dispatch recorder. Raises TypeError for unhashable
    static config (array-valued kwargs etc.)."""
    if static_key is not None:
        return static_key
    pos_set = set(arr_pos)
    return (
        _static_key(fn),
        tuple(arr_pos),
        len(args),
        tuple(_static_key(a) for i, a in enumerate(args)
              if i not in pos_set),
        _static_key(kwargs),
    )


def _maybe_defer(fn, args, kwargs, name, record, sync_outputs, cacheable,
                 static_key, arr_pos, arrays, NDArray, size):
    """Record the call into the thread's pending bulk segment instead of
    dispatching. Returns ``(result, key)``: lazy-handle NDArrays, or
    ``_NOT_DEFERRED`` when the op must dispatch directly (flushing the
    segment first, so program order is preserved across the deferral
    boundary). ``key`` is the computed static key (``None`` if never
    computed, ``_KEY_ERR`` if unhashable) — apply's jit-cache block
    reuses it instead of walking the closure twice."""
    import weakref

    _eng = engine
    if not sync_outputs or not cacheable:
        # tape-replay internals (create_graph) and explicitly uncacheable
        # calls: correctness first — flush and dispatch directly
        _eng.flush_current("undeferrable")
        return _NOT_DEFERRED, None
    try:
        key = _op_static_key(fn, args, kwargs, arr_pos, static_key)
    except TypeError:
        _eng.flush_current("undeferrable")
        return _NOT_DEFERRED, _KEY_ERR
    if key in _EAGER_JIT_SKIP:
        # known jit-incompatible / RNG-consuming op: never defer
        _eng.flush_current("undeferrable")
        return _NOT_DEFERRED, key

    if arr_pos and len(arr_pos) == len(args) and not kwargs:
        closed = fn
    else:
        template = list(args)

        def closed(*xs):
            for pos, x in zip(arr_pos, xs):
                template[pos] = x
            return fn(*template, **kwargs)

    from ..ndarray.ndarray import _tracked

    rec_on = record and autograd.is_recording()
    for _attempt in (0, 1, 2, 3):
        seg = _eng._segment_for_record(size)
        ins = []
        tracked_flags = []
        reflush = False
        for a in arrays:
            # getattr: sparse subclasses store indices+values, no _buf slot
            buf = getattr(a, "_buf", None) \
                if getattr(a, "_view_parent", None) is None else None
            if type(buf) is _eng._LazyRef and buf.value is None \
                    and buf.err is None and buf.seg is seg:
                if rec_on and getattr(a, "_leaf", None) is not None:
                    # a LEAF handle whose value is still a pending lazy
                    # (deferred `w -= ...`): unbulked semantics route the
                    # gradient to the leaf slot, NOT through the deferred
                    # update chain — flush, then record it as a concrete
                    # tracked external input
                    reflush = True
                    break
                ins.append(buf)
                tracked_flags.append(buf.tainted or _tracked(a))
            else:
                # concrete (or foreign-segment / failed lazy: _data forces
                # and surfaces the error exactly like a materialization)
                d = a._data
                if isinstance(d, _jax_tracer()):
                    # inside someone's trace (hybridize/cachedop): the
                    # tracer must flow through THAT trace — recording it
                    # into a host segment would leak it. Dispatch
                    # directly, no flush.
                    return _NOT_DEFERRED, key
                ins.append(d)
                tracked_flags.append(_tracked(a))
        if reflush:
            _eng.flush_current("tape")
            continue
        if seg.done:
            # scanning an input forced THIS segment to flush (a view over
            # a lazy parent, a shared handle materialized mid-scan): the
            # captured segment can't record anymore — restart on a fresh
            # one (inputs are concrete now, so this converges)
            continue
        akey = (key, tuple((tuple(x.shape), str(x.dtype)) for x in ins))
        try:
            info = _AVAL_CACHE.get(akey)
        except TypeError:
            info = ("nodefer",)
        if info is None:
            info = _infer_avals(closed, ins, akey)
        if info[0] != "ok":
            _eng.flush_current("undeferrable")
            return _NOT_DEFERRED, key
        _, flat_avals, single, was_list = info
        recording = rec_on and any(tracked_flags)
        refs = seg.record(closed, key, ins, arrays, tracked_flags,
                          flat_avals, single, was_list, recording, name)
        if refs is not None:
            break
        # None: a cross-thread materialization flushed the segment between
        # the scan and the record — restart on a fresh segment
    else:
        # pathologically unstable: dispatch directly
        return _NOT_DEFERRED, key

    wrapped = []
    for r in refs:
        w = NDArray._from_lazy(r)
        r.owner = weakref.ref(w)
        wrapped.append(w)
    if len(seg.ops) >= seg.size:
        seg.flush("size")
    if single:
        return wrapped[0], key
    return (wrapped if was_list else tuple(wrapped)), key


def _infer_avals(closed, ins, akey):
    """Abstract-trace ``closed`` (jax.eval_shape) to learn output
    structure without dispatching; detects RNG consumption (those ops are
    never deferred — a cached segment trace would bake their keys)."""
    import jax

    from .. import random as _rng

    specs = [jax.ShapeDtypeStruct(tuple(x.shape), x.dtype) for x in ins]
    marks = _rng.probe_marks()
    mark = marks[0]
    try:
        out = jax.eval_shape(closed, *specs)
    except Exception:
        _rng.rewind_probe(marks)
        info = ("nodefer",)
    else:
        if _rng.consume_count() != mark:
            # the probe burned real keys tracing an RNG op: un-draw them
            # so seeded streams match a bulk-disabled run exactly
            _rng.rewind_probe(marks)
            info = ("nodefer",)
        else:
            single = not isinstance(out, (tuple, list))
            was_list = isinstance(out, list)
            flat = [out] if single else list(out)
            if any(not hasattr(o, "shape") or not hasattr(o, "dtype")
                   for o in flat):
                info = ("nodefer",)  # non-array outputs: dispatch directly
            else:
                info = ("ok",
                        tuple((tuple(o.shape), o.dtype) for o in flat),
                        single, was_list)
    if len(_AVAL_CACHE) >= _EAGER_JIT_MAX:
        # a wiped aval cache re-pays one eval_shape per bulked op until
        # it refills — same attributability discipline as the jit cache
        global _AVAL_CLEARS

        _AVAL_CACHE.clear()
        _AVAL_CLEARS += 1
        _note_cache_clear("deferred-dispatch aval cache",
                          "aval_cache_clears", _AVAL_CLEARS)
    _AVAL_CACHE[akey] = info
    return info


def apply(fn, args, kwargs=None, name="", record=True, sync_outputs=True,
          static_key=None, cacheable=True):
    """Invoke ``fn`` on a mix of NDArray / scalar / array args.

    NDArray positions become differentiable primal inputs; everything else is
    closed over as a constant. When autograd is recording and any NDArray
    input is tracked, forward runs under ``jax.vjp`` and a tape node is
    created (``Imperative::RecordOp`` analog).

    ``static_key`` — optional precomputed hashable identity of everything
    static about this call (op + config). When given, the eager jit cache
    uses it directly instead of walking ``fn``'s closure, which keeps the
    per-call overhead down on hot namespace ops.
    """
    import jax

    prof = _PROF
    if prof is not None and prof.IMPERATIVE:
        # opt-in per-op call counters (profile_imperative): the role of the
        # reference's imperative API events, without the always-on cost
        prof.count_op(name or getattr(fn, "__name__", "op"))

    NDArray = _ndarray_cls()
    kwargs = kwargs or {}
    arr_pos = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    arrays = [args[i] for i in arr_pos]

    op_key = None  # static key computed by the defer fork, reused below
    if engine._BULK_POSSIBLE:
        # deferred eager dispatch (engine bulk segments): record instead
        # of dispatching when a segment is open and the op is deferrable.
        # The op:dispatch fault site fires per recorded op at flush.
        bulk_n = engine._active_bulk_size()
        if bulk_n > 1:
            deferred, op_key = _maybe_defer(
                fn, args, kwargs, name, record, sync_outputs, cacheable,
                static_key, arr_pos, arrays, NDArray, bulk_n)
            if deferred is not _NOT_DEFERRED:
                return deferred

    flt = _FAULTS
    if flt is not None:
        # injected transient dispatch error (resilience.faults): raised
        # BEFORE any tape/cache mutation so a caller-level retry sees a
        # clean slate. No info payload — building one per dispatch would
        # cost more than the site check itself
        flt.check("op:dispatch")

    engine._count_dispatch()
    datas = tuple(a._data for a in arrays)

    if arr_pos and len(arr_pos) == len(args) and not kwargs:
        closed = fn
    else:
        template = list(args)

        def closed(*xs):
            for pos, x in zip(arr_pos, xs):
                template[pos] = x
            return fn(*template, **kwargs)

    cache_key = None
    cache_candidate = None
    rng_mark = 0
    jit_hit_key = None  # verified-cacheable op: fast fwd AND cached-vjp bwd
    if _eager_jit_enabled and cacheable and op_key is not _KEY_ERR:
        try:
            key = op_key if op_key is not None \
                else _op_static_key(fn, args, kwargs, arr_pos, static_key)
            if key not in _EAGER_JIT_SKIP:
                jitted = _EAGER_JIT_CACHE.get(key)
                if jitted is not None:
                    closed = jitted
                    jit_hit_key = key
                else:
                    from .. import random as _rng

                    # jit now, publish to the cache only after the call
                    # traced without drawing an RNG key (a cached trace
                    # would replay the same baked key forever)
                    rng_mark = _rng.consume_count()
                    cache_key = key
                    _uncached_closed = closed
                    cache_candidate = jax.jit(closed)
                    closed = cache_candidate
        except TypeError:
            pass  # unhashable static config (e.g. array-valued kwargs)

    from ..ndarray.ndarray import _tracked, _slot_of

    recording = (
        record
        and autograd.is_recording()
        and any(_tracked(a) for a in arrays)
    )
    was_list = False

    def normalized(*xs):
        # multi-output ops (split, qr, slogdet...) may return lists or
        # namedtuples; the tape's cotangent convention is plain tuples, so
        # normalize at the vjp boundary (remembering listness so the caller
        # sees the same container type with or without recording)
        nonlocal was_list
        r = closed(*xs)
        if isinstance(r, list):
            was_list = True
            return tuple(r)
        if isinstance(r, tuple) and hasattr(r, "_fields"):
            return tuple(r)
        return r

    try:
        if recording and jit_hit_key is not None:
            # verified-cacheable op (cache hit => its trace is RNG-free and
            # jit-compatible): run the compiled forward directly — no
            # per-call jax.vjp retrace — and defer backward to the cached
            # compiled vjp. First encounters and RNG ops keep the eager
            # jax.vjp path (an RNG op's backward replay would re-draw keys
            # and mismatch the forward's masks).
            outs = normalized(*datas)
            vjp_fn = _make_cached_vjp(normalized, datas, jit_hit_key)
        elif recording:
            outs, vjp_fn = jax.vjp(normalized, *datas)
        else:
            outs = normalized(*datas)
    except Exception:
        if cache_candidate is None:
            raise
        # maybe jit-specific (value-dependent Python: dynamic output
        # shapes, host reads) — retry eagerly; only a SUCCESSFUL retry
        # proves jit-incompatibility and justifies skipping the cache
        # forever (a plain user error must not poison the key)
        closed = _uncached_closed
        cache_candidate = None
        if recording:
            outs, vjp_fn = jax.vjp(normalized, *datas)
        else:
            outs = normalized(*datas)
        _EAGER_JIT_SKIP.add(cache_key)

    if cache_candidate is not None:
        from .. import random as _rng

        if _rng.consume_count() == rng_mark:
            if len(_EAGER_JIT_CACHE) >= _EAGER_JIT_MAX:
                global _EAGER_JIT_CLEARS

                _EAGER_JIT_CACHE.clear()
                _EAGER_BWD_CACHE.clear()
                _EAGER_JIT_CLEARS += 1
                _note_cache_clear(count=_EAGER_JIT_CLEARS)
            _EAGER_JIT_CACHE[cache_key] = cache_candidate
        else:
            _EAGER_JIT_SKIP.add(cache_key)

    single = not isinstance(outs, (tuple, list))
    flat = [outs] if single else list(outs)
    wrapped = [NDArray(o) for o in flat]

    if recording:
        if not single and len(flat) == 1:
            # the tape walk hands a bare leaf when there's one output, but
            # jax.vjp of a 1-tuple-returning fn wants a 1-tuple cotangent
            raw_vjp = vjp_fn
            vjp_fn = (lambda ct, _raw=raw_vjp:
                      _raw(ct if isinstance(ct, tuple) else (ct,)))
        node = autograd.TapeNode(
            vjp_fn,
            [_slot_of(a) for a in arrays],
            [(o.shape, o.dtype) for o in flat],
            name=name or getattr(fn, "__name__", "op"),
            # saved for create_graph=True: the backward walk re-linearizes
            # this op as a recorded op (higher-order autograd)
            fwd_fn=normalized,
            in_arrays=list(arrays),
        )
        # create_graph's replay must hand jax.vjp a cotangent matching the
        # forward's output structure: bare leaf vs 1-tuple
        node.out_container = not single
        for i, w in enumerate(wrapped):
            w._tape = (node, i)

    if sync_outputs:
        engine.maybe_sync(flat)
    if single:
        return wrapped[0]
    return list(wrapped) if was_list else type(outs)(wrapped)


def apply_out(fn, args, kwargs=None, out=None, name=""):
    """Like :func:`apply` but honoring an ``out=`` destination NDArray."""
    res = apply(fn, args, kwargs, name=name)
    if out is None:
        return res
    if isinstance(out, (tuple, list)):
        for o, r in zip(out, res):
            o._set_data_internal(r._lazy_or_data())
        return out
    out._set_data_internal(res._lazy_or_data())
    out._tape = getattr(res, "_tape", None)
    return out
