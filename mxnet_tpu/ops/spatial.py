"""Spatial-transformer op family (reference:
``src/operator/grid_generator.cc``, ``src/operator/bilinear_sampler.cc``,
``src/operator/spatial_transformer.cc``).

TPU-first design: the sampler is pure gather + arithmetic (fully
differentiable through jnp.take/where, so vjp gives the reference's
backward kernels for free), grids use the reference's normalized [-1, 1]
coordinate convention, and everything jits — these run inside
``hybridize`` like any other op.
"""
from __future__ import annotations

from ..base import MXNetError
from .registry import apply as _apply
from .registry import register as _register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _affine_grid(theta, h, w):
    """(N, 6) affine -> (N, 2, h, w) sampling grid, normalized [-1, 1]."""
    jnp = _jnp()
    n = theta.shape[0]
    theta = theta.reshape(n, 2, 3)
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, h*w)
    out = jnp.einsum("nij,jk->nik", theta, coords)              # (n, 2, h*w)
    return out.reshape(n, 2, h, w)


def grid_generator(data, transform_type="affine", target_shape=None):
    """Generate a sampling grid (reference ``GridGenerator``):
    ``affine``: data (N, 6) row-major 2x3 matrices; ``warp``: data
    (N, 2, H, W) pixel-offset flow added to the identity grid."""
    jnp = _jnp()
    if transform_type == "affine":
        if target_shape is None:
            raise MXNetError("grid_generator(affine) needs target_shape")
        h, w = int(target_shape[0]), int(target_shape[1])

        def f(t):
            return _affine_grid(t, h, w)

        return _apply(f, (data,), name="grid_generator:affine")
    if transform_type == "warp":

        def f(flow):
            n, _, h, w = flow.shape
            base = _affine_grid(
                jnp.tile(jnp.asarray([1.0, 0.0, 0.0, 0.0, 1.0, 0.0]),
                         (n, 1)), h, w)
            # flow is in pixels; normalize to the [-1, 1] grid scale
            fx = flow[:, 0] * (2.0 / max(w - 1, 1))
            fy = flow[:, 1] * (2.0 / max(h - 1, 1))
            return base + jnp.stack([fx, fy], axis=1)

        return _apply(f, (data,), name="grid_generator:warp")
    raise MXNetError(f"unknown transform_type {transform_type!r}")


def _j_bilinear_sample(data, grid):
    """data (N,C,H,W), grid (N,2,Ho,Wo) in [-1,1] -> (N,C,Ho,Wo);
    out-of-range samples contribute 0 (reference zero padding)."""
    jnp = _jnp()
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0   # (n, ho, wo)
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        # validity BEFORE clipping; invalid taps weighted 0
        valid = ((xi >= 0) & (xi <= w - 1) & (yi >= 0)
                 & (yi <= h - 1))[:, None]
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        flat = data.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, 1, -1)
        vals = jnp.take_along_axis(
            flat, jnp.broadcast_to(idx, (n, c, idx.shape[-1])), axis=2)
        vals = vals.reshape(n, c, *xi.shape[1:])
        return jnp.where(valid, vals, 0.0)

    out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[:, None]
           + gather(y0, x0 + 1) * (wx * (1 - wy))[:, None]
           + gather(y0 + 1, x0) * ((1 - wx) * wy)[:, None]
           + gather(y0 + 1, x0 + 1) * (wx * wy)[:, None])
    return out


def bilinear_sampler(data, grid, **kwargs):  # pylint: disable=unused-argument
    """Bilinear sampling by a normalized grid (reference
    ``BilinearSampler``)."""
    return _apply(_j_bilinear_sample, (data, grid),
                  name="bilinear_sampler")


def spatial_transformer(data, loc, target_shape=None,
                        transform_type="affine",
                        sampler_type="bilinear", **kwargs):  # pylint: disable=unused-argument
    """Affine spatial transformer network head (reference
    ``SpatialTransformer``): loc (N, 6) -> grid -> bilinear sample."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError(
            "spatial_transformer supports transform_type='affine' + "
            "sampler_type='bilinear' (reference parity)")
    if target_shape is None:
        target_shape = data.shape[2:]
    h, w = int(target_shape[0]), int(target_shape[1])

    def f(d, t):
        return _j_bilinear_sample(d, _affine_grid(t, h, w))

    return _apply(f, (data, loc), name="spatial_transformer")


for _name in ("grid_generator", "bilinear_sampler", "spatial_transformer"):
    _register(_name, globals()[_name], wrapper=True)
