"""Spatial-transformer op family (reference:
``src/operator/grid_generator.cc``, ``src/operator/bilinear_sampler.cc``,
``src/operator/spatial_transformer.cc``).

TPU-first design: the sampler is pure gather + arithmetic (fully
differentiable through jnp.take/where, so vjp gives the reference's
backward kernels for free), grids use the reference's normalized [-1, 1]
coordinate convention, and everything jits — these run inside
``hybridize`` like any other op.
"""
from __future__ import annotations

from ..base import MXNetError
from .registry import apply as _apply
from .registry import register as _register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _affine_grid(theta, h, w):
    """(N, 6) affine -> (N, 2, h, w) sampling grid, normalized [-1, 1]."""
    jnp = _jnp()
    n = theta.shape[0]
    theta = theta.reshape(n, 2, 3)
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, h*w)
    out = jnp.einsum("nij,jk->nik", theta, coords)              # (n, 2, h*w)
    return out.reshape(n, 2, h, w)


def grid_generator(data, transform_type="affine", target_shape=None):
    """Generate a sampling grid (reference ``GridGenerator``):
    ``affine``: data (N, 6) row-major 2x3 matrices; ``warp``: data
    (N, 2, H, W) pixel-offset flow added to the identity grid."""
    jnp = _jnp()
    if transform_type == "affine":
        if target_shape is None:
            raise MXNetError("grid_generator(affine) needs target_shape")
        h, w = int(target_shape[0]), int(target_shape[1])

        def f(t):
            return _affine_grid(t, h, w)

        return _apply(f, (data,), name="grid_generator:affine")
    if transform_type == "warp":

        def f(flow):
            n, _, h, w = flow.shape
            base = _affine_grid(
                jnp.tile(jnp.asarray([1.0, 0.0, 0.0, 0.0, 1.0, 0.0]),
                         (n, 1)), h, w)
            # flow is in pixels; normalize to the [-1, 1] grid scale
            fx = flow[:, 0] * (2.0 / max(w - 1, 1))
            fy = flow[:, 1] * (2.0 / max(h - 1, 1))
            return base + jnp.stack([fx, fy], axis=1)

        return _apply(f, (data,), name="grid_generator:warp")
    raise MXNetError(f"unknown transform_type {transform_type!r}")


def _j_bilinear_sample(data, grid):
    """data (N,C,H,W), grid (N,2,Ho,Wo) in [-1,1] -> (N,C,Ho,Wo);
    out-of-range samples contribute 0 (reference zero padding)."""
    jnp = _jnp()
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0   # (n, ho, wo)
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        # validity BEFORE clipping; invalid taps weighted 0
        valid = ((xi >= 0) & (xi <= w - 1) & (yi >= 0)
                 & (yi <= h - 1))[:, None]
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        flat = data.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, 1, -1)
        vals = jnp.take_along_axis(
            flat, jnp.broadcast_to(idx, (n, c, idx.shape[-1])), axis=2)
        vals = vals.reshape(n, c, *xi.shape[1:])
        return jnp.where(valid, vals, 0.0)

    out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[:, None]
           + gather(y0, x0 + 1) * (wx * (1 - wy))[:, None]
           + gather(y0 + 1, x0) * ((1 - wx) * wy)[:, None]
           + gather(y0 + 1, x0 + 1) * (wx * wy)[:, None])
    return out


def bilinear_sampler(data, grid, **kwargs):  # pylint: disable=unused-argument
    """Bilinear sampling by a normalized grid (reference
    ``BilinearSampler``)."""
    return _apply(_j_bilinear_sample, (data, grid),
                  name="bilinear_sampler")


def spatial_transformer(data, loc, target_shape=None,
                        transform_type="affine",
                        sampler_type="bilinear", **kwargs):  # pylint: disable=unused-argument
    """Affine spatial transformer network head (reference
    ``SpatialTransformer``): loc (N, 6) -> grid -> bilinear sample."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError(
            "spatial_transformer supports transform_type='affine' + "
            "sampler_type='bilinear' (reference parity)")
    if target_shape is None:
        target_shape = data.shape[2:]
    h, w = int(target_shape[0]), int(target_shape[1])

    def f(d, t):
        return _j_bilinear_sample(d, _affine_grid(t, h, w))

    return _apply(f, (data, loc), name="spatial_transformer")


def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation / cost volume (reference
    ``src/operator/correlation.cc`` CorrelationForward): for each output
    position, correlate a kernel patch of data1 with patches of data2 at
    all displacements in a (2d/stride2+1)^2 neighborhood; mean over the
    patch and channels (/ kernel²·C).

    TPU formulation: one `jnp.roll`-free shifted slice per displacement
    (static python loop over the displacement grid — its size is a
    compile-time constant), each an elementwise multiply + channel/patch
    reduction XLA fuses; no gather kernels needed.
    """
    jnp = _jnp()
    if kernel_size % 2 == 0:
        raise MXNetError("correlation kernel_size must be odd")
    kr = (kernel_size - 1) // 2
    border = max_displacement + kr
    ngr = max_displacement // stride2           # neighborhood grid radius
    ngw = 2 * ngr + 1

    def f(d1, d2):
        import math as _m

        b, c, h, w = d1.shape
        ph, pw = h + 2 * pad_size, w + 2 * pad_size
        top_h = _m.ceil((ph - 2 * border) / stride1)
        top_w = _m.ceil((pw - 2 * border) / stride1)
        pad = ((0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size))
        p1 = jnp.pad(d1, pad)
        p2 = jnp.pad(d2, pad)
        sumelems = kernel_size * kernel_size * c
        outs = []
        for tc in range(ngw * ngw):
            s2o = (tc % ngw - ngr) * stride2    # x displacement
            s2p = (tc // ngw - ngr) * stride2   # y displacement
            acc = None
            for hh in range(kernel_size):
                for ww in range(kernel_size):
                    y1 = max_displacement + hh
                    x1 = max_displacement + ww
                    a = p1[:, :,
                           y1:y1 + (top_h - 1) * stride1 + 1:stride1,
                           x1:x1 + (top_w - 1) * stride1 + 1:stride1]
                    bb = p2[:, :,
                            y1 + s2p:y1 + s2p + (top_h - 1) * stride1 + 1:stride1,
                            x1 + s2o:x1 + s2o + (top_w - 1) * stride1 + 1:stride1]
                    term = a * bb if is_multiply else jnp.abs(a - bb)
                    t = term.sum(axis=1)
                    acc = t if acc is None else acc + t
            outs.append(acc / sumelems)
        return jnp.stack(outs, axis=1)  # (B, ngw*ngw, top_h, top_w)

    return _apply(f, (data1, data2), name="correlation")


def deformable_convolution(data, offset, weight, bias=None, kernel=None,
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=None, num_group=1,
                           num_deformable_group=1, no_bias=False, **kwargs):  # pylint: disable=unused-argument
    """Deformable convolution v1 (reference
    ``src/operator/contrib/nn/deformable_im2col.h`` semantics): each
    kernel tap's sampling position is shifted by a learned per-position
    offset, sampled bilinearly (zero outside), then the ordinary conv
    reduction.

    TPU formulation: build the deformed patch tensor with the same
    gather-based bilinear sampler the spatial family uses, then contract
    patches × weights with one einsum (MXU); the reference's
    deformable_im2col + GEMM, minus the hand-written scatter backward —
    jax.vjp differentiates the sampler.

    offset layout (reference): (B, 2 * dg * kh * kw, OH, OW) ordered
    [dg][kh][kw][(y, x)].
    """
    jnp = _jnp()

    def f(x, off, wgt, *mb):
        import jax

        b, c, h, w = x.shape
        o, cg, kh, kw = wgt.shape
        dg = num_deformable_group
        sy, sx = stride
        dy, dx = dilate
        py, px = pad
        oh = (h + 2 * py - dy * (kh - 1) - 1) // sy + 1
        ow = (w + 2 * px - dx * (kw - 1) - 1) // sx + 1
        # base sampling positions per tap (kh*kw, oh, ow)
        gy0 = (jnp.arange(oh) * sy - py)[None, :, None]
        gx0 = (jnp.arange(ow) * sx - px)[None, None, :]
        ky = (jnp.arange(kh) * dy)[:, None, None, None]
        kx = (jnp.arange(kw) * dx)[None, :, None, None]
        base_y = jnp.broadcast_to(gy0[None] + ky, (kh, kw, oh, ow))
        base_x = jnp.broadcast_to(gx0[None] + kx, (kh, kw, oh, ow))
        off = off.reshape(b, dg, kh, kw, 2, oh, ow)
        pos_y = base_y[None, None] + off[:, :, :, :, 0]  # (B,dg,kh,kw,oh,ow)
        pos_x = base_x[None, None] + off[:, :, :, :, 1]

        def sample_group(xg, py_, px_):
            # xg (C/dg, H, W); py_/px_ (kh,kw,oh,ow) -> (C/dg,kh,kw,oh,ow)
            y0 = jnp.floor(py_)
            x0 = jnp.floor(px_)
            wy = py_ - y0
            wx = px_ - x0

            def gat(yi, xi):
                valid = (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
                yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                v = xg[:, yc, xc]  # (C/dg, kh, kw, oh, ow)
                return jnp.where(valid[None], v, 0.0)

            return (gat(y0, x0) * ((1 - wy) * (1 - wx))[None]
                    + gat(y0, x0 + 1) * ((1 - wy) * wx)[None]
                    + gat(y0 + 1, x0) * (wy * (1 - wx))[None]
                    + gat(y0 + 1, x0 + 1) * (wy * wx)[None])

        cg_d = c // dg
        patches = jax.vmap(              # over batch
            jax.vmap(sample_group))(     # over deformable groups
            x.reshape(b, dg, cg_d, h, w), pos_y, pos_x)
        patches = patches.reshape(b, c, kh, kw, oh, ow)
        # grouped contraction: (B,G,C/G,kh,kw,oh,ow) x (G,O/G,C/G,kh,kw)
        g = num_group
        pg = patches.reshape(b, g, c // g, kh, kw, oh, ow)
        wg = wgt.reshape(g, o // g, cg, kh, kw)
        out = jnp.einsum(
            "bgcxhw,gocx->bgohw",
            pg.reshape(b, g, c // g, kh * kw, oh, ow),
            wg.reshape(g, o // g, cg, kh * kw))
        out = out.reshape(b, o, oh, ow)
        if mb:
            out = out + mb[0].reshape(1, -1, 1, 1)
        return out

    args = (data, offset, weight) if (no_bias or bias is None) \
        else (data, offset, weight, bias)
    return _apply(f, args, name="deformable_convolution")


for _name in ("grid_generator", "bilinear_sampler", "spatial_transformer",
              "correlation", "deformable_convolution"):
    _register(_name, globals()[_name], wrapper=True)
