"""Fused multi-layer RNN/LSTM/GRU (reference op: ``src/operator/rnn.cc`` —
the cuDNN-backed fused ``RNN`` op behind ``gluon.rnn.{RNN,LSTM,GRU}``).

TPU design: per layer/direction, the input projection is hoisted out of the
time loop as ONE large ``(T*N, C) @ (C, G*H)`` matmul (MXU-sized), and only
the recurrent ``h @ Whh`` stays inside a ``lax.scan`` — one XLA while-loop
whose compile time is independent of sequence length.
"""
from __future__ import annotations

from ..base import MXNetError


def _gate_counts(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def _layer_scan(x, h0, c0, wih, whh, bih, bhh, mode, reverse=False):
    """One direction of one layer. x: (T, N, C) -> (T, N, H)."""
    import jax
    import jax.numpy as jnp

    H = whh.shape[1]
    gx = jnp.einsum("tnc,gc->tng", x, wih) + bih  # hoisted input projection

    if mode == "lstm":
        def step(carry, g_t):
            h, c = carry
            gates = g_t + h @ whh.T + bhh
            i = jax.nn.sigmoid(gates[:, 0:H])
            f = jax.nn.sigmoid(gates[:, H:2 * H])
            g = jnp.tanh(gates[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(gates[:, 3 * H:4 * H])
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c), h

        (h_T, c_T), out = jax.lax.scan(step, (h0, c0), gx, reverse=reverse)
        return out, h_T, c_T
    if mode == "gru":
        def step(h, g_t):
            hh = h @ whh.T + bhh
            r = jax.nn.sigmoid(g_t[:, 0:H] + hh[:, 0:H])
            z = jax.nn.sigmoid(g_t[:, H:2 * H] + hh[:, H:2 * H])
            n = jnp.tanh(g_t[:, 2 * H:3 * H] + r * hh[:, 2 * H:3 * H])
            h = (1.0 - z) * n + z * h
            return h, h

        h_T, out = jax.lax.scan(step, h0, gx, reverse=reverse)
        return out, h_T, None
    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

    def step(h, g_t):
        h = act(g_t + h @ whh.T + bhh)
        return h, h

    h_T, out = jax.lax.scan(step, h0, gx, reverse=reverse)
    return out, h_T, None


def rnn_fused(data, h0, c0, weights, mode, num_layers, bidirectional,
              dropout=0.0, train=False, rng_key=None):
    """Run the fused stack. ``data``: (T, N, C) raw jax array.

    ``weights``: flat list ordered [wih, whh, bih, bhh] per (layer,
    direction), directions l then r within a layer (reference param naming
    ``{l,r}{i}_i2h_weight`` — ``python/mxnet/gluon/rnn/rnn_layer.py``).
    ``h0``/``c0``: (L*D, N, H). Returns (out, h_T, c_T or None).
    """
    import jax
    import jax.numpy as jnp

    D = 2 if bidirectional else 1
    x = data
    h_outs, c_outs = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(D):
            idx = (layer * D + d) * 4
            wih, whh, bih, bhh = weights[idx:idx + 4]
            s = layer * D + d
            out, h_T, c_T = _layer_scan(
                x, h0[s], c0[s] if c0 is not None else None,
                wih, whh, bih, bhh, mode, reverse=(d == 1))
            outs.append(out)
            h_outs.append(h_T)
            if c_T is not None:
                c_outs.append(c_T)
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if dropout > 0 and train and layer < num_layers - 1:
            if rng_key is None:
                raise MXNetError("dropout inside fused rnn needs an rng key")
            keep = 1.0 - dropout
            mask = jax.random.bernoulli(
                jax.random.fold_in(rng_key, layer), keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0)
    h_stack = jnp.stack(h_outs)
    c_stack = jnp.stack(c_outs) if c_outs else None
    return x, h_stack, c_stack
