"""Detection operator family: multibox priors/targets/detections, box_nms,
ROIAlign — TPU-first (static shapes, vmapped batch, lax.scan where the
reference loops).

Reference semantics: ``src/operator/contrib/multibox_prior.cc`` (anchor
math verified against the kernel at lines 30-73), ``multibox_target.cc``
(bipartite + threshold matching, variance-encoded box targets),
``multibox_detection.cc`` (per-anchor class pick + NMS),
``src/operator/contrib/bounding_box.cc`` (box_nms contract: sorted by
score, pruned entries filled with -1), ``src/operator/contrib/roi_align.cc``
(Caffe2-style bilinear sampling, ``aligned`` offset).

Design notes (SURVEY §7 hard part 3 — padding discipline): every output
has a static shape; "suppressed"/"invalid" slots are filled with -1
instead of shrinking, exactly the reference's convention, which is what
makes these ops jit-compatible on TPU.
"""
from __future__ import annotations

import math

from .registry import apply as _apply
from .registry import register as _register


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# multibox_prior
# ---------------------------------------------------------------------------


def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Generate SSD anchor boxes for each feature-map cell of ``data``
    (B, C, H, W) → (1, H*W*(num_sizes+num_ratios-1), 4) corner boxes.

    Anchor set per cell (reference multibox_prior.cc:44-70): every size
    with the first ratio, then the first size with every remaining ratio;
    w = size * H/W * sqrt(ratio) / 2, h = size / sqrt(ratio) / 2 around
    the (offset-shifted, step-scaled) cell center.
    """
    jnp = _jnp()
    sizes = [float(s) for s in sizes]
    ratios = [float(r) for r in ratios]
    in_h, in_w = int(data.shape[2]), int(data.shape[3])
    step_y = steps[0] if steps[0] > 0 else 1.0 / in_h
    step_x = steps[1] if steps[1] > 0 else 1.0 / in_w

    def f(_x):
        cy = (jnp.arange(in_h, dtype=jnp.float32) + offsets[0]) * step_y
        cx = (jnp.arange(in_w, dtype=jnp.float32) + offsets[1]) * step_x
        wh = []
        r0 = math.sqrt(ratios[0]) if ratios else 1.0
        for s in sizes:
            wh.append((s * in_h / in_w * r0 / 2, s / r0 / 2))
        for r in ratios[1:]:
            sr = math.sqrt(r)
            wh.append((sizes[0] * in_h / in_w * sr / 2, sizes[0] / sr / 2))
        ws = jnp.asarray([w for w, _ in wh], jnp.float32)
        hs = jnp.asarray([h for _, h in wh], jnp.float32)
        # (H, W, A, 4)
        cxg = jnp.broadcast_to(cx[None, :, None], (in_h, in_w, len(wh)))
        cyg = jnp.broadcast_to(cy[:, None, None], (in_h, in_w, len(wh)))
        out = jnp.stack([cxg - ws, cyg - hs, cxg + ws, cyg + hs], axis=-1)
        out = out.reshape(1, -1, 4)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        return out

    return _apply(f, (data,), name="multibox_prior")


# ---------------------------------------------------------------------------
# box helpers
# ---------------------------------------------------------------------------


def _iou_corner(a, b):
    """Pairwise IoU of corner boxes a (N,4) × b (M,4) → (N, M)."""
    jnp = _jnp()
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(
        a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(
        b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_to_corner(b):
    jnp = _jnp()
    half_w, half_h = b[..., 2] / 2, b[..., 3] / 2
    return jnp.stack([b[..., 0] - half_w, b[..., 1] - half_h,
                      b[..., 0] + half_w, b[..., 1] + half_h], axis=-1)


# ---------------------------------------------------------------------------
# box_nms
# ---------------------------------------------------------------------------


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Non-maximum suppression (reference ``bounding_box.cc`` box_nms).

    ``data``: (..., N, K) with score at ``score_index`` and 4 coords at
    ``coord_start``. Output has identical shape: entries are sorted by
    descending score with pruned/invalid entries written as all -1 —
    static-shape NMS, no dynamic compaction.
    """
    import jax

    jnp = _jnp()

    def nms_single(d):
        n = d.shape[0]
        scores = d[:, score_index]
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= d[:, id_index] != background_id
        boxes = d[:, coord_start:coord_start + 4]
        if in_format == "center":
            boxes = _center_to_corner(boxes)
        order = jnp.argsort(jnp.where(valid, -scores, jnp.inf))
        ds = d[order]
        boxes = boxes[order]
        valid = valid[order]
        if topk > 0:
            valid &= jnp.arange(n) < topk
        iou = _iou_corner(boxes, boxes)
        if id_index >= 0 and not force_suppress:
            same = ds[:, id_index][:, None] == ds[:, id_index][None, :]
            iou = jnp.where(same, iou, 0.0)

        def body(keep, i):
            sup = (iou[i] > overlap_thresh) & keep[i] & \
                (jnp.arange(n) > i)
            return keep & ~sup, None

        keep, _ = jax.lax.scan(body, valid, jnp.arange(n))
        # survivors first (stable by score order), pruned rows = -1
        out_order = jnp.argsort(~keep, stable=True)
        ds = ds[out_order]
        keep_s = keep[out_order]
        # emit in out_format regardless of in_format (the two args are
        # independent in the reference bounding_box.cc)
        if out_format != in_format:
            if out_format == "corner":  # center in -> corner out
                ds = ds.at[:, coord_start:coord_start + 4].set(
                    boxes[out_order])
            else:                       # corner in -> center out
                c = ds[:, coord_start:coord_start + 4]
                ctr = jnp.stack([(c[:, 0] + c[:, 2]) / 2,
                                 (c[:, 1] + c[:, 3]) / 2,
                                 c[:, 2] - c[:, 0], c[:, 3] - c[:, 1]],
                                axis=-1)
                ds = ds.at[:, coord_start:coord_start + 4].set(ctr)
        return jnp.where(keep_s[:, None], ds, -1.0)

    def f(x):
        flat = x.reshape((-1,) + x.shape[-2:])
        out = __import__("jax").vmap(nms_single)(flat)
        return out.reshape(x.shape)

    return _apply(f, (data,), name="box_nms")


# ---------------------------------------------------------------------------
# multibox_target
# ---------------------------------------------------------------------------


def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (reference ``multibox_target.cc``).

    anchor (1, N, 4) corners; label (B, M, 5) rows [cls, xmin, ymin,
    xmax, ymax] with cls = -1 padding; cls_pred (B, C+1, N) used only for
    hard-negative mining. Returns (box_target (B, N*4), box_mask (B, N*4),
    cls_target (B, N)) where cls_target is gt_class+1 for matched anchors,
    0 for background, ``ignore_label`` for mined-away negatives.

    Matching = reference two-phase: greedy bipartite (each gt claims its
    best unclaimed anchor, in global-IoU order, via lax.scan) then
    threshold matching (anchor's best gt if IoU > overlap_threshold).
    """
    import jax

    jnp = _jnp()

    def one_sample(anc, lab, cpred):
        n = anc.shape[0]
        m = lab.shape[0]
        gt_valid = lab[:, 0] >= 0
        iou = jnp.where(gt_valid[None, :], _iou_corner(anc, lab[:, 1:5]),
                        -1.0)  # (N, M)

        # phase 1: bipartite, M rounds of global argmax
        def bip(carry, _):
            iou_w, match = carry
            flat = jnp.argmax(iou_w)
            ai = (flat // m).astype(jnp.int32)
            gi = (flat % m).astype(jnp.int32)
            best = iou_w[ai, gi]
            do = best > 1e-12
            match = jnp.where(do, match.at[ai].set(gi), match)
            iou_w = jnp.where(do, iou_w.at[ai, :].set(-1.0), iou_w)
            iou_w = jnp.where(do, iou_w.at[:, gi].set(-1.0), iou_w)
            return (iou_w, match), None

        match0 = jnp.full((n,), -1, jnp.int32)
        (_, match), _ = jax.lax.scan(bip, (iou, match0), None, length=m)

        # phase 2: threshold matching for still-unmatched anchors
        best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
        best_iou = jnp.max(iou, axis=1)
        match = jnp.where((match < 0) & (best_iou > overlap_threshold),
                          best_gt, match)

        matched = match >= 0
        gt = lab[jnp.maximum(match, 0)]
        cls_t = jnp.where(matched, gt[:, 0] + 1.0, 0.0)

        if negative_mining_ratio > 0:
            # hard-negative mining (reference multibox_target.cc): an
            # unmatched anchor is a negative CANDIDATE only if its best
            # IoU < negative_mining_thresh (higher-overlap unmatched
            # anchors are "too hard" and ignored); candidates are ranked
            # by ASCENDING softmax probability of the background class
            # (multibox_target.cc:219-237 sorts SortElemDescend(-prob) —
            # lowest background confidence = hardest negative first) and
            # the top ratio*num_pos (>= minimum_negative_samples) train as
            # background — every other unmatched anchor gets ignore_label.
            bg_prob = jax.nn.softmax(cpred, axis=0)[0, :]
            cand = (~matched) & (best_iou < negative_mining_thresh)
            num_pos = jnp.sum(matched)
            quota = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                minimum_negative_samples)
            rank = jnp.argsort(jnp.argsort(
                jnp.where(cand, bg_prob, jnp.inf)))
            keep_neg = cand & (rank < quota)
            cls_t = jnp.where(matched, cls_t,
                              jnp.where(keep_neg, 0.0,
                                        float(ignore_label)))

        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        ax = (anc[:, 0] + anc[:, 2]) / 2
        ay = (anc[:, 1] + anc[:, 3]) / 2
        gw = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-8)
        gh = jnp.maximum(gt[:, 4] - gt[:, 2], 1e-8)
        gx = (gt[:, 1] + gt[:, 3]) / 2
        gy = (gt[:, 2] + gt[:, 4]) / 2
        t = jnp.stack([
            (gx - ax) / aw / variances[0],
            (gy - ay) / ah / variances[1],
            jnp.log(gw / aw) / variances[2],
            jnp.log(gh / ah) / variances[3],
        ], axis=-1)
        mask = matched[:, None].astype(anc.dtype)
        box_t = (t * mask).reshape(-1)
        box_m = jnp.broadcast_to(mask, (n, 4)).reshape(-1)
        return box_t, box_m, cls_t

    def f(anc, lab, cpred):
        import jax as _jax

        a = anc[0]
        return _jax.vmap(lambda l, cp: one_sample(a, l, cp))(lab, cpred)

    return _apply(f, (anchor, label, cls_pred), name="multibox_target")


# ---------------------------------------------------------------------------
# multibox_detection
# ---------------------------------------------------------------------------


def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode SSD predictions into detections (reference
    ``multibox_detection.cc``): per anchor pick the best non-background
    class, decode the variance-encoded offsets against its anchor, then
    NMS. Output (B, N, 6) rows [class_id, score, xmin, ymin, xmax, ymax];
    invalid/suppressed rows are -1. class ids are 0-based with background
    removed (reference convention: out id = argmax class - 1)."""
    jnp = _jnp()

    def f(cp, lp, anc):
        b, n = cp.shape[0], anc.shape[1]
        a = anc[0]
        aw = a[:, 2] - a[:, 0]
        ah = a[:, 3] - a[:, 1]
        ax = (a[:, 0] + a[:, 2]) / 2
        ay = (a[:, 1] + a[:, 3]) / 2
        loc = lp.reshape(b, n, 4)
        cx = loc[..., 0] * variances[0] * aw + ax
        cy = loc[..., 1] * variances[1] * ah + ay
        w = jnp.exp(loc[..., 2] * variances[2]) * aw
        h = jnp.exp(loc[..., 3] * variances[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        probs = cp.transpose(0, 2, 1)  # (B, N, C+1)
        masked = probs.at[..., background_id].set(-jnp.inf)
        best = jnp.argmax(masked, axis=-1)
        score = jnp.take_along_axis(probs, best[..., None],
                                    axis=-1)[..., 0]
        cls_id = jnp.where(best > background_id, best - 1, best).astype(
            cp.dtype)
        ok = score > threshold
        rows = jnp.concatenate([
            jnp.where(ok, cls_id, -1.0)[..., None],
            jnp.where(ok, score, 0.0)[..., None], boxes], axis=-1)
        return rows

    rows = _apply(f, (cls_prob, loc_pred, anchor),
                  name="multibox_detection_decode")
    return box_nms(rows, overlap_thresh=nms_threshold, valid_thresh=0.0,
                   topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                   force_suppress=force_suppress)


def roi_pooling(data, rois, pooled_size, spatial_scale=1.0):
    """Classic max ROIPooling (reference ``src/operator/roi_pooling.cc``):
    ROI coords are rounded to the feature grid, each output bin max-pools
    its quantized pixel span; empty bins yield 0.

    TPU formulation: instead of per-bin dynamic slices (data-dependent
    sizes don't jit), every pixel computes its bin index and a masked
    scatter-max accumulates — one static-shape pass per ROI.
    """
    import jax

    jnp = _jnp()
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))

    def f(x, r):
        B, C, H, W = x.shape

        def one_roi(roi):
            bidx = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
            roi_h = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
            roi_w = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
            # reference bin spans OVERLAP: bin b covers
            # [floor(b*roi/p), ceil((b+1)*roi/p)) — a pixel can belong to
            # two adjacent bins, so membership is a (bins, pixels) mask,
            # not an inverse map
            hs = jnp.arange(H)
            ws = jnp.arange(W)
            bh = jnp.arange(ph).astype(jnp.float32)
            bw = jnp.arange(pw).astype(jnp.float32)
            h_rel = (hs - y1)[None, :]
            w_rel = (ws - x1)[None, :]
            mh = ((h_rel >= jnp.floor(bh[:, None] * roi_h / ph))
                  & (h_rel < jnp.ceil((bh[:, None] + 1) * roi_h / ph))
                  & (hs >= y1)[None, :] & (hs <= y2)[None, :])  # (ph, H)
            mw = ((w_rel >= jnp.floor(bw[:, None] * roi_w / pw))
                  & (w_rel < jnp.ceil((bw[:, None] + 1) * roi_w / pw))
                  & (ws >= x1)[None, :] & (ws <= x2)[None, :])  # (pw, W)
            img = x[bidx]  # (C, H, W)
            neg = jnp.finfo(img.dtype).min
            # two-stage masked max: over W per bw, then over H per bh
            tmp = jnp.max(
                jnp.where(mw[None, None], img[:, :, None, :], neg),
                axis=-1)  # (C, H, pw)
            out = jnp.max(
                jnp.where(mh[None, :, :, None], tmp[:, None], neg),
                axis=2)  # (C, ph, pw)
            return jnp.where(out == neg, 0.0, out)

        return jax.vmap(one_roi)(r)

    return _apply(f, (data, rois), name="roi_pooling")


# ---------------------------------------------------------------------------
# roi_align
# ---------------------------------------------------------------------------


def roi_align(data, rois, pooled_size, spatial_scale=1.0, sample_ratio=-1,
              position_sensitive=False, aligned=False):
    """ROIAlign (reference ``roi_align.cc``, the Caffe2 kernel semantics):
    average of bilinear samples on a regular grid inside each output bin.

    data (B, C, H, W); rois (R, 5) rows [batch_idx, x1, y1, x2, y2] in
    image coordinates (scaled by ``spatial_scale``). ``aligned=True``
    applies the half-pixel offset fix. ``sample_ratio`` < 1 falls back to
    a static 2x2 sample grid (the adaptive ceil(roi/bin) grid of the
    reference is value-dependent, incompatible with static shapes; 2 is
    Detectron's default).
    """
    import jax

    jnp = _jnp()
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))
    sr = sample_ratio if sample_ratio and sample_ratio > 0 else 2

    def f(x, r):
        B, C, H, W = x.shape
        off = 0.5 if aligned else 0.0

        def one_roi(roi):
            bidx = roi[0].astype(jnp.int32)
            x1 = roi[1] * spatial_scale - off
            y1 = roi[2] * spatial_scale - off
            x2 = roi[3] * spatial_scale - off
            y2 = roi[4] * spatial_scale - off
            rw = x2 - x1
            rh = y2 - y1
            if not aligned:  # reference: force malformed ROIs to 1x1
                rw = jnp.maximum(rw, 1.0)
                rh = jnp.maximum(rh, 1.0)
            bin_w = rw / pw
            bin_h = rh / ph
            # sample grid: (ph*sr, pw*sr) points
            gy = y1 + (jnp.arange(ph * sr) + 0.5) * rh / (ph * sr)
            gx = x1 + (jnp.arange(pw * sr) + 0.5) * rw / (pw * sr)

            def bilinear(img, ys, xs):
                # Caffe2 contract: points beyond the image by MORE than
                # one pixel contribute zero; in-range points clamp
                ok = ((ys >= -1.0) & (ys <= H))[:, None] \
                    & ((xs >= -1.0) & (xs <= W))[None, :]
                ys = jnp.clip(ys, 0.0, H - 1.0)
                xs = jnp.clip(xs, 0.0, W - 1.0)
                y0 = jnp.floor(ys).astype(jnp.int32)
                x0 = jnp.floor(xs).astype(jnp.int32)
                y1_ = jnp.minimum(y0 + 1, H - 1)
                x1_ = jnp.minimum(x0 + 1, W - 1)
                wy = ys - y0
                wx = xs - x0
                g = lambda yy, xx: img[:, yy, :][:, :, xx]  # noqa: E731
                v = (g(y0, x0) * ((1 - wy)[None, :, None] * (1 - wx)[None, None, :])
                     + g(y1_, x0) * (wy[None, :, None] * (1 - wx)[None, None, :])
                     + g(y0, x1_) * ((1 - wy)[None, :, None] * wx[None, None, :])
                     + g(y1_, x1_) * (wy[None, :, None] * wx[None, None, :]))
                return jnp.where(ok[None], v, 0.0)  # (C, len(ys), len(xs))

            img = x[bidx]
            samples = bilinear(img, gy, gx)  # (C, ph*sr, pw*sr)
            pooled = samples.reshape(C, ph, sr, pw, sr).mean(axis=(2, 4))
            if position_sensitive:
                # PS-ROIAlign (R-FCN, reference roi_align.cc PS mode):
                # bin (i, j) of output channel c reads input channel
                # c*ph*pw + i*pw + j
                c_out = C // (ph * pw)
                sel = (jnp.arange(c_out)[:, None, None] * (ph * pw)
                       + jnp.arange(ph)[None, :, None] * pw
                       + jnp.arange(pw)[None, None, :])  # (c_out, ph, pw)
                pooled = pooled[sel,
                                jnp.arange(ph)[None, :, None],
                                jnp.arange(pw)[None, None, :]]
            del bin_w, bin_h
            return pooled

        return jax.vmap(one_roi)(r)

    return _apply(f, (data, rois), name="roi_align")


def box_iou(lhs, rhs, fmt="corner"):
    """Batched pairwise IoU (reference ``bounding_box.cc``
    ``_contrib_box_iou``/``_npx_box_iou``): lhs (..., N, 4) × rhs
    (..., M, 4) → (..., N, M). Invalid boxes (non-positive extent, e.g.
    the -1 padding convention) score 0 against everything."""
    import jax

    def f(a, b):
        jnp = _jnp()
        if fmt == "center":
            a, b = _center_to_corner(a), _center_to_corner(b)
        batch = a.shape[:-2]
        fa = a.reshape((-1,) + a.shape[-2:])
        fb = b.reshape((-1,) + b.shape[-2:])
        out = jax.vmap(_iou_corner)(fa, fb)
        return out.reshape(batch + out.shape[-2:])

    return _apply(f, (lhs, rhs), name="box_iou")


# registry entries: list_ops parity + mx.sym.<op> symbol constructors
for _name in ("multibox_prior", "multibox_target", "multibox_detection",
              "box_nms", "box_iou", "roi_align", "roi_pooling"):
    _register(_name, globals()[_name], wrapper=True)
