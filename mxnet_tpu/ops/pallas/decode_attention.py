"""Fused decode attention: single-query-timestep attention over the KV ring.

The serving decode step (``Generator``'s T=1 call) spends its time in
``ops/nn.cached_attention`` — the PR-5 mul+reduce formulation that buys
bitwise prefill/decode parity by materializing a (B, H, T, S, D) broadcast.
This module is the fast rung behind it: a flash-style Pallas kernel that
streams the KV ring through VMEM in 128-wide blocks with the valid-length
mask (``position <= start_pos``) applied in-kernel, plus a fused-einsum XLA
fallback for shapes/platforms the kernel does not cover (T>1 verify blocks,
CPU without interpret mode).

Layout: GQA is handled natively — the kernel takes *unexpanded* K/V of
shape (B, KV, S, D) and puts the G = H // KV query heads of each KV group
on the sublane axis, so head_dim 64/128 models run full (8, 128) f32 tiles
without materializing the head-repeated K/V that the baseline path needs.

int8 KV rings dequantize in-kernel: pass ``k_scale``/``v_scale`` of shape
(B, KV, S) (per-token-per-head scales from ``ops/nn.kv_cache_write_q``) and
the kernel widens int8 blocks to f32 right next to the MXU dot, so the ring
stays half-size in HBM end to end.

Introspection follows ``flash_attention``'s conventions: ``last_path()``
reports which implementation the last call traced ("pallas" | "xla"),
``force_path()`` overrides routing, ``use_interpret(True)`` runs the kernel
through the Pallas interpreter on CPU. Decode-shaped calls (T == 1) that
land on the XLA fallback additionally record a flight-recorder note and
bump the ``serve.decode_fallbacks`` counter so silent slow-path serving is
diagnosable from ``/metrics``.

Loop-carried ``start_pos`` (multi-step decode, PR 19): every input —
including ``start_pos`` — may be a traced value inside a
``lax.while_loop`` body, advancing per iteration while the kernel stays
the SAME compiled program. The contract that makes this work: routing
(``_supports_pallas``) depends only on static shapes/dtypes/platform,
never on start_pos values; the valid-length mask and the block-skip
predicate consume start_pos as data (SMEM scalars / in-kernel compares);
and the path/fallback records fire at TRACE time, so one super-step
compile records exactly one path decision no matter how many iterations
the loop later runs. ``reset_fallbacks()`` rezeroes the cumulative
counter for tests/bench rungs that assert a clean kernel run.
"""
from __future__ import annotations

import math

_NEG_INF = -1e30  # finite "minus infinity": keeps fully-masked rows NaN-free
_BLOCK = 128      # lane width / KV stream block size


def natural_block() -> int:
    """The kernel's KV stream block width (lane tile) — the natural page
    size for the paged KV allocator (``serve.kv_blocks``): a pool page
    that matches it means the kernel's block-skip mask
    (``run = si * bk <= sp``) skips whole unreached pages, so a slot
    only ever pays compute for pages its sequence has actually
    reached."""
    return _BLOCK

# trace-time record of which implementation the last call chose
# ("pallas" | "xla"); tests and bench assert the kernel actually ran.
_LAST_PATH = None

_INTERPRET = False


def use_interpret(flag: bool) -> None:
    """Force Pallas interpreter mode (CPU testing of the TPU kernel)."""
    global _INTERPRET
    _INTERPRET = bool(flag)


_FORCE_PATH = None


def force_path(path) -> None:
    """Override decode-attention path selection: None | 'xla' | 'pallas'."""
    global _FORCE_PATH
    if path not in (None, "xla", "pallas"):
        raise ValueError(f"force_path: {path!r} not in (None,'xla','pallas')")
    _FORCE_PATH = path


def last_path():
    return _LAST_PATH


# Cumulative count of decode-shaped (T == 1) calls that fell back to the
# XLA path. Trace-time, so steady-state serving bumps it once per compiled
# signature, not once per step — a nonzero value after warmup means the
# fast rung is not actually serving from the kernel.
_FALLBACKS = 0


def fallback_count() -> int:
    return _FALLBACKS


def reset_fallbacks() -> None:
    """Rezero the cumulative decode-fallback counter (tests / bench
    rungs that assert a specific trace produced zero fallbacks — the
    counter is trace-time, so differencing around a cached replay would
    always read 0 even on a fallback path)."""
    global _FALLBACKS
    _FALLBACKS = 0


def _record_fallback(reason, shape):
    global _FALLBACKS
    _FALLBACKS += 1
    from ...profiler import core as _prof
    from ...profiler import recorder as _recorder

    args = {"reason": reason, "shape": "x".join(str(d) for d in shape)}
    _recorder.note("fallback", "serve.decode_fallback", args)
    _prof.incr_counter("serve.decode_fallbacks", cat="serve")
    _prof.record_instant("serve.decode_fallback", cat="serve", args=args)


def _round_up(x, m):
    return (x + m - 1) // m * m


def _platform_of(x):
    try:
        return list(x.devices())[0].platform
    except Exception:
        import jax
        return jax.default_backend()


def _supports_pallas(q, k):
    """Kernel coverage: one query timestep, lane-width-bounded head_dim,
    grouped heads, and a TPU (or interpreter) underneath."""
    if q.ndim != 4 or k.ndim != 4:
        return False
    b, h, t, d = q.shape
    if t != 1 or d > 256:
        return False
    if h % k.shape[1] != 0:
        return False
    if _INTERPRET:
        return True
    return _platform_of(q) in ("tpu", "axon")


def _xla_decode(q, k, v, start_pos, scale, k_scale, v_scale):
    """Fused-einsum fallback: grouped-heads attention over the ring with
    the same ``position <= start_pos + t`` mask as the kernel. Handles any
    T (the speculative verify block reuses it at T = k+1) and dequantizes
    int8 rings inline."""
    import jax
    import jax.numpy as jnp

    b, h, t, d = q.shape
    kv, s = k.shape[1], k.shape[2]
    g = h // kv
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    if k_scale is not None:
        kf = kf * k_scale[..., None].astype(jnp.float32)
    if v_scale is not None:
        vf = vf * v_scale[..., None].astype(jnp.float32)
    qg = q.astype(jnp.float32).reshape(b, kv, g, t, d)
    scores = jnp.einsum("bngtd,bnsd->bngts", qg, kf) * scale
    pos = start_pos.astype(jnp.int32)[:, None] + jnp.arange(t, dtype=jnp.int32)
    valid = jnp.arange(s, dtype=jnp.int32)[None, None, :] <= pos[:, :, None]
    scores = jnp.where(valid[:, None, None, :, :], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bngts,bnsd->bngtd", w, vf)
    return out.reshape(b, h, t, d).astype(q.dtype)


def _decode_kernel(quant, kv, g, d, bk, n_k, scale,
                   sp_ref, q_ref, k_ref, v_ref, *rest):
    """One (batch·kv_head) program: stream S in ``bk`` blocks with flash
    running-max/sum accumulators; the G grouped query heads sit on the
    sublane axis so the whole group shares each K/V block load."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if quant:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest

    si = pl.program_id(1)
    sp = sp_ref[jax.lax.div(pl.program_id(0), jnp.int32(kv))]

    @pl.when(si == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # block needed iff its first position is still <= start_pos
    run = si * bk <= sp

    @pl.when(run)
    def _body():
        qb = q_ref[0].astype(jnp.float32)          # (Gp, Dp)
        kb = k_ref[0].astype(jnp.float32)          # (bk, Dp)
        vb = v_ref[0].astype(jnp.float32)
        if quant:
            kb = kb * ks_ref[0, 0][:, None]
            vb = vb * vs_ref[0, 0][:, None]
        sc = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (Gp, bk)
        kpos = si * bk + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        sc = jnp.where(kpos <= sp, sc, _NEG_INF)

        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_cur = jnp.max(sc, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(sc - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        pv = jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv

    @pl.when(si == n_k - 1)
    def _finish():
        l = l_ref[...]
        l = jnp.where(l == 0.0, 1.0, l)  # padded sublane rows: emit zeros
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _pallas_decode(q, k, v, start_pos, scale, k_scale, v_scale):
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, _, d = q.shape
    kv, s = k.shape[1], k.shape[2]
    g = h // kv
    quant = k_scale is not None

    bk = _BLOCK
    sp_len = _round_up(s, bk)
    dp = _round_up(d, _BLOCK)
    gp = _round_up(g, 8)  # f32 sublane tile

    q4 = q.reshape(b, kv, g, d).reshape(b * kv, g, d)
    q4 = jnp.pad(q4, ((0, 0), (0, gp - g), (0, dp - d)))
    k3 = k.reshape(b * kv, s, d)
    v3 = v.reshape(b * kv, s, d)
    k3 = jnp.pad(k3, ((0, 0), (0, sp_len - s), (0, dp - d)))
    v3 = jnp.pad(v3, ((0, 0), (0, sp_len - s), (0, dp - d)))
    n_k = sp_len // bk

    in_specs = [
        pl.BlockSpec((b,), lambda i, j: (jnp.int32(0),),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, gp, dp), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, bk, dp), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, bk, dp), lambda i, j: (i, j, 0)),
    ]
    args = [start_pos.astype(jnp.int32), q4, k3, v3]
    if quant:
        ks3 = k_scale.astype(jnp.float32).reshape(b * kv, 1, s)
        vs3 = v_scale.astype(jnp.float32).reshape(b * kv, 1, s)
        ks3 = jnp.pad(ks3, ((0, 0), (0, 0), (0, sp_len - s)))
        vs3 = jnp.pad(vs3, ((0, 0), (0, 0), (0, sp_len - s)))
        # (1, 1, bk) block over the 3D scale array — same shape trick as
        # the flash kernel's lse output: TPU rejects a 2D (1, bk) block.
        in_specs += [pl.BlockSpec((1, 1, bk), lambda i, j: (i, 0, j)),
                     pl.BlockSpec((1, 1, bk), lambda i, j: (i, 0, j))]
        args += [ks3, vs3]

    kernel = functools.partial(_decode_kernel, quant, kv, g, d, bk, n_k,
                               scale)
    out = pl.pallas_call(
        kernel,
        grid=(b * kv, n_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gp, dp), lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * kv, gp, dp), q.dtype),
        scratch_shapes=[pltpu.VMEM((gp, 1), jnp.float32),
                        pltpu.VMEM((gp, 1), jnp.float32),
                        pltpu.VMEM((gp, dp), jnp.float32)],
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_INTERPRET,
    )(*args)
    out = out[:, :g, :d].reshape(b, kv, g, d).reshape(b, h, 1, d)
    return out


def decode_attention(q, k, v, start_pos, scale=None,
                     k_scale=None, v_scale=None):
    """Attention for the serving decode step.

    q: (B, H, T, D); k/v: (B, KV, S, D) *unexpanded* GQA rings (f32, or
    int8 with (B, KV, S) ``k_scale``/``v_scale``); start_pos: (B,) int32.
    Position ``s`` attends iff ``s <= start_pos[b] + t``. Returns
    (B, H, T, D).
    """
    global _LAST_PATH
    d = q.shape[-1]
    sc = scale if scale is not None else 1.0 / math.sqrt(d)

    use_pallas = _supports_pallas(q, k)
    if _FORCE_PATH == "xla":
        use_pallas = False
    elif _FORCE_PATH == "pallas":
        if not use_pallas:
            raise ValueError(
                f"force_path('pallas'): unsupported decode shape "
                f"q={q.shape} k={k.shape} on {_platform_of(q)}")
        use_pallas = True

    if use_pallas:
        _LAST_PATH = "pallas"
        return _pallas_decode(q, k, v, start_pos, sc, k_scale, v_scale)
    _LAST_PATH = "xla"
    if q.shape[2] == 1:  # decode-shaped call missed the kernel: diagnose
        reason = "interpret_off_cpu" if _platform_of(q) not in (
            "tpu", "axon") else "unsupported_shape"
        if _FORCE_PATH == "xla":
            reason = "forced_xla"
        _record_fallback(reason, q.shape)
    return _xla_decode(q, k, v, start_pos, sc, k_scale, v_scale)
