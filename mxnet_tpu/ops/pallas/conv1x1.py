"""Fused 1x1-conv pair as a single Pallas TPU kernel (VERDICT r4 #1b).

A 1x1 convolution in channels-last layout IS a matmul over the flattened
batch*spatial rows: ``(M, C1) @ (C1, Cm)``.  ResNet-style bottlenecks
chain two of them (expand/reduce) with a relu between — the shape
`exp/conv_chain_probe.json` measured at 0.22-0.41 MXU utilization under
XLA's conv lowering (`stage2_1x1_pair`: 43 TF/s of the chip's 197).

This kernel computes ``relu(a1(x @ w1)) @ w2 -> relu(a2(.))`` for one
row-tile per grid step, keeping the mid-channel intermediate ``h`` in
VMEM — it never touches HBM, so the pair's traffic drops from
x + h + h + y to x + y.  ``a1``/``a2`` are optional per-channel affines
(folded BatchNorm for inference-time use).  Both matmuls land on the
MXU with f32 accumulation.

The pair's fused arithmetic intensity: per row it does 4*C1*Cm flops
against 4*C1 bytes of x-in + y-out traffic, i.e. AI = Cm flops/byte.
At the stage2 shape (Cm=128) that is below the v5e machine balance of
240 (197e12/819e9) — HBM-bound: the kernel's ceiling is ~0.53 MXU, not
1.0.  Measured verdict vs the XLA conv and XLA matmul formulations:
`exp/pallas_1x1_probe.json`, summarized in PERF.md.

Reference context: the reference's bottleneck 1x1s are cuDNN conv calls
(`/root/reference/src/operator/nn/convolution.cc`) — there is no fused
pair there; this is TPU-first design on the shape the probe named.
"""
from __future__ import annotations

import functools


def _kernel(x_ref, w1_ref, w2_ref, s1_ref, b1_ref, s2_ref, b2_ref, o_ref):
    import jax.numpy as jnp

    x = x_ref[...]
    h = jnp.dot(x, w1_ref[...], preferred_element_type=jnp.float32)
    h = h * s1_ref[0] + b1_ref[0]
    h = jnp.maximum(h, 0.0).astype(x.dtype)
    y = jnp.dot(h, w2_ref[...], preferred_element_type=jnp.float32)
    y = y * s2_ref[0] + b2_ref[0]
    o_ref[...] = jnp.maximum(y, 0.0).astype(x.dtype)


def _kernel_res(x_ref, res_ref, w1_ref, w2_ref, s1_ref, b1_ref, s2_ref,
                b2_ref, o_ref):
    """Pair with a residual folded between the matmuls: computes
    ``relu(a2(relu(a1(x @ w1) + res) @ w2))`` — the cross-block
    bottleneck-boundary motif (c3 -> bn3 -> +skip -> relu -> next c1 ->
    bn1 -> relu) in channels-last rows."""
    import jax.numpy as jnp

    x = x_ref[...]
    h = jnp.dot(x, w1_ref[...], preferred_element_type=jnp.float32)
    h = h * s1_ref[0] + b1_ref[0] + res_ref[...].astype(jnp.float32)
    h = jnp.maximum(h, 0.0).astype(x.dtype)
    y = jnp.dot(h, w2_ref[...], preferred_element_type=jnp.float32)
    y = y * s2_ref[0] + b2_ref[0]
    o_ref[...] = jnp.maximum(y, 0.0).astype(x.dtype)


def _kernel_res2(x_ref, res_ref, w1_ref, w2_ref, s1_ref, b1_ref, s2_ref,
                 b2_ref, o_mid_ref, o_ref):
    """_kernel_res that ALSO writes the mid value ``relu(a1(x@w1)+res)``
    — a fused ResNet stage needs it as the NEXT block's residual."""
    import jax.numpy as jnp

    x = x_ref[...]
    h = jnp.dot(x, w1_ref[...], preferred_element_type=jnp.float32)
    h = h * s1_ref[0] + b1_ref[0] + res_ref[...].astype(jnp.float32)
    h = jnp.maximum(h, 0.0).astype(x.dtype)
    o_mid_ref[...] = h
    y = jnp.dot(h, w2_ref[...], preferred_element_type=jnp.float32)
    y = y * s2_ref[0] + b2_ref[0]
    o_ref[...] = jnp.maximum(y, 0.0).astype(x.dtype)


@functools.partial(
    __import__("jax").jit,
    static_argnames=("block_rows", "return_mid", "interpret"))
def conv1x1_pair(x, w1, w2, scale1=None, bias1=None, scale2=None,
                 bias2=None, residual=None, *, block_rows=1024,
                 return_mid=False, interpret=False):
    """relu(a2((relu(a1(x @ w1) [+ residual])) @ w2)), mid in VMEM.

    x: (..., C1) channels-last; any leading shape (flattened to rows).
    w1: (C1, Cm), w2: (Cm, C1out). scale/bias: optional (Cm,)/(C1out,)
    per-channel affines applied before each relu (folded BN).
    residual: optional (..., Cm) skip input added after the first
    affine, before its relu — the bottleneck block-boundary motif.
    return_mid (requires residual): also return the post-residual mid
    ``relu(a1(x@w1)+res)`` — (out, mid); a fused ResNet stage feeds
    mid forward as the next boundary's residual.
    Rows are zero-padded up to a block_rows multiple and sliced back.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    c1, cm = w1.shape
    cout = w2.shape[1]
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, c1)
    pad = (-m) % block_rows
    if pad:
        x2 = jnp.concatenate(
            [x2, jnp.zeros((pad, c1), x2.dtype)], axis=0)
    mp = m + pad
    r2 = None
    if residual is not None:
        r2 = residual.reshape(m, cm).astype(x.dtype)
        if pad:
            r2 = jnp.concatenate(
                [r2, jnp.zeros((pad, cm), r2.dtype)], axis=0)

    # per-channel affines as (1, C) 2-D — TPU VMEM blocks must be >=2-D
    one = jnp.ones((), jnp.float32)
    zero = jnp.zeros((), jnp.float32)
    s1 = jnp.broadcast_to(one if scale1 is None else scale1, (1, cm)) \
        .astype(jnp.float32)
    b1 = jnp.broadcast_to(zero if bias1 is None else bias1, (1, cm)) \
        .astype(jnp.float32)
    s2 = jnp.broadcast_to(one if scale2 is None else scale2, (1, cout)) \
        .astype(jnp.float32)
    b2 = jnp.broadcast_to(zero if bias2 is None else bias2, (1, cout)) \
        .astype(jnp.float32)

    # index-map constants must be jnp.int32 built INSIDE the map (a bare
    # Python 0 lowers to i64 and Mosaic rejects the mixed index tuple;
    # a captured tracer is rejected by pallas itself)
    full = lambda s: pl.BlockSpec(  # noqa: E731
        s, lambda i: (jnp.int32(0),) * len(s))
    # When the pair is channel-stable (C1 == Cout, no row padding) let
    # the output reuse x's buffer: grid step i reads exactly the rows it
    # writes, so aliasing is safe, and it lets XLA elide the full-array
    # copy it otherwise inserts when the call sits in a loop carry
    # (measured: the copy alone costs as much as the kernel at stage2).
    # JAX still copies defensively if x is live elsewhere.
    alias = {0: 0} if (cout == c1 and pad == 0) else {}
    row_spec = lambda c: pl.BlockSpec(  # noqa: E731
        (block_rows, c), lambda i: (i, jnp.int32(0)))
    in_specs = [row_spec(c1)]
    operands = [x2]
    if r2 is not None:
        in_specs.append(row_spec(cm))
        operands.append(r2)
    in_specs += [full((c1, cm)), full((cm, cout)), full((1, cm)),
                 full((1, cm)), full((1, cout)), full((1, cout))]
    operands += [w1, w2, s1, b1, s2, b2]
    if return_mid:
        if r2 is None:
            raise ValueError("return_mid requires residual")
        kern = _kernel_res2
        out_specs = [row_spec(cm), row_spec(cout)]
        out_shape = [jax.ShapeDtypeStruct((mp, cm), x.dtype),
                     jax.ShapeDtypeStruct((mp, cout), x.dtype)]
        alias = {}  # mid output shares no buffer with x
    else:
        kern = _kernel if r2 is None else _kernel_res
        out_specs = row_spec(cout)
        out_shape = jax.ShapeDtypeStruct((mp, cout), x.dtype)
    out = pl.pallas_call(
        kern,
        grid=(mp // block_rows,),
        input_output_aliases=alias,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*operands)
    if return_mid:
        mid, y = out
        if pad:
            mid, y = mid[:m], y[:m]
        return (y.reshape(*lead, cout), mid.reshape(*lead, cm))
    if pad:
        out = out[:m]
    return out.reshape(*lead, cout)
