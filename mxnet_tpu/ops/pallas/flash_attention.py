"""Flash attention: Pallas TPU kernels (fwd + bwd) + XLA reference fallback.

The reference framework has no attention op at all — only fused matmul
helpers (``src/operator/contrib/transformer.cc``); SURVEY.md §5 requires the
TPU build to introduce memory-efficient attention natively.

Design (flash-attention-2 schedule adapted to TPU tiling):

* forward: grid ``(batch*heads, q_blocks, k_blocks)``; K/V blocks stream
  from HBM through VMEM with running max/sum accumulators in fp32 VMEM
  scratch; the log-sum-exp per query row is a second output so the backward
  can recompute probabilities blockwise.
* backward: two Pallas kernels — ``dq`` over ``(bh, q_blocks, k_blocks)``
  and ``dk/dv`` over ``(bh, k_blocks, q_blocks)`` — each recomputing the
  probability block from (q, k, lse) in VMEM, so training memory stays
  O(T·block) instead of the O(T²) score materialization.
* masking: *valid-length* masking (the BERT ``valid_length`` path) happens
  inside the kernel from a ``(B, 1)`` int32 SMEM input — no dense (T, T)
  mask is ever materialized on the flash path. Arbitrary dense masks fall
  back to the XLA reference implementation.
* shapes: head_dim is zero-padded to the 128 lane width (so the model-zoo
  head_dim 64 runs on the MXU at full tile) and sequence lengths are padded
  to the 128 block size; padded key columns are masked via the same
  valid-length mechanism and padded query rows are sliced off.

Set ``use_interpret(True)`` to run the same kernels through the Pallas
interpreter on CPU (used by the test suite on the virtual device mesh).
"""
from __future__ import annotations

import functools
import math

_NEG_INF = -1e30  # finite "minus infinity": keeps fully-masked rows NaN-free
_BLOCK = 128      # MXU tile edge: minimum q/k block size and lane padding
_MAX_BLOCK_FWD = 1024   # VMEM-bounded: scores tile 1024^2 f32 = 4 MB
_MAX_BLOCK_BWD = 512    # bwd holds 3 score-sized tiles (p, dp, ds)

# trace-time record of which implementation the last attention() call chose
# ("pallas" | "xla"); tests and bench assert the flash path actually ran.
_LAST_PATH = None

_INTERPRET = False


def use_interpret(flag: bool) -> None:
    """Force Pallas interpreter mode (CPU testing of the TPU kernels)."""
    global _INTERPRET
    _INTERPRET = bool(flag)


# bench/test override of the empirical crossover routing: None (measured
# routing), "xla" (force fallback — the bench ablation arm), or "pallas"
# (force the kernel where it supports the shape).
_FORCE_PATH = None


def force_path(path) -> None:
    """Override attention path selection: None | 'xla' | 'pallas'."""
    global _FORCE_PATH
    if path not in (None, "xla", "pallas"):
        raise ValueError(f"force_path: {path!r} not in (None,'xla','pallas')")
    _FORCE_PATH = path


def last_path():
    return _LAST_PATH


def _reference_attention(q, k, v, mask=None, causal=False, scale=None,
                         valid_length=None):
    """XLA attention: materializes scores; fallback for dense masks/CPU."""
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s
    tq, tk = scores.shape[-2], scores.shape[-1]
    if causal:
        cm = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(cm, scores, _NEG_INF)
    if valid_length is not None:
        kpos = jnp.arange(tk).reshape(1, 1, 1, tk)
        vl = valid_length.astype(jnp.int32).reshape(-1, 1, 1, 1)
        scores = jnp.where(kpos < vl, scores, _NEG_INF)
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, _NEG_INF)
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    # fully-masked rows (e.g. valid_length 0, or causal with tq > tk) emit
    # zeros — not a uniform average over keys the mask excluded; this is the
    # semantics the flash kernels implement and gradients stay zero too
    alive = jnp.max(scores, axis=-1, keepdims=True) > _NEG_INF / 2
    w = jnp.where(alive, w, 0)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def _round_up(x, m):
    return (x + m - 1) // m * m


def _pick_block(t, maxb):
    """Largest block ≤ maxb whose T-padding wastes ≤12.5%: big blocks keep
    the MXU busy (measured 30→50 TF/s going 512→1024 at T=8192), small
    sequences shouldn't pay for block-rounding."""
    tp = _round_up(t, _BLOCK)
    c = maxb
    while c > _BLOCK:
        if _round_up(tp, c) <= 1.125 * tp:
            return c
        c //= 2
    return _BLOCK


def _pad_qkv(q, k, v, bq, bk):
    """Zero-pad (B,H,T,D) to block-aligned (B,H,Tp,Dp); zeros are masked
    out by the in-kernel valid-length clamp, so padding never leaks."""
    import jax.numpy as jnp

    b, h, tq, d = q.shape
    tk = k.shape[2]
    tqp, tkp, dp = _round_up(tq, bq), _round_up(tk, bk), _round_up(d, _BLOCK)

    def pad(x, tp):
        t = x.shape[2]
        if t == tp and x.shape[3] == dp:
            return x
        return jnp.pad(x, ((0, 0), (0, 0), (0, tp - t), (0, dp - x.shape[3])))

    return pad(q, tqp), pad(k, tkp), pad(v, tkp)


def _kvalid_array(valid_length, b, tk):
    """(B,) int32 of per-batch valid key counts (clamped to true Tk)."""
    import jax.numpy as jnp

    if valid_length is None:
        return jnp.full((b,), tk, dtype=jnp.int32)
    vl = jnp.minimum(valid_length.astype(jnp.int32), tk)
    return vl.reshape(b)


def _score_mask(sc, qi, ki, kvalid, causal, causal_off, block_q, block_k):
    """Apply causal + valid-length masking to one (block_q, block_k) tile."""
    import jax
    import jax.numpy as jnp

    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = kpos < kvalid
    if causal:
        keep = jnp.logical_and(keep, kpos <= qpos + causal_off)
    return jnp.where(keep, sc, jnp.float32(_NEG_INF))


def _flash_fwd(q, k, v, kvalid, causal, causal_off, scale, bq, bk):
    """Pallas forward on padded (B,H,Tp,Dp); returns (out, lse)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    n_q, n_k = tq // bq, tk // bk

    def kernel(vl_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
               m_scr, l_scr, acc_scr):
        qi, ki = pl.program_id(1), pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        # whole (B,) lengths vector lives in SMEM; pick this program's batch
        kvalid = vl_ref[jax.lax.div(pl.program_id(0), jnp.int32(h))]
        run = ki * bk < kvalid
        if causal:
            run = jnp.logical_and(run, ki * bk <= qi * bq + bq - 1 + causal_off)

        @pl.when(run)
        def _body():
            qb = q_ref[0].astype(jnp.float32) * jnp.float32(scale)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            sc = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            sc = _score_mask(sc, qi, ki, kvalid, causal, causal_off, bq, bk)
            m_prev = m_scr[:]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
            # dead rows (everything masked): exp(-1e30 - -1e30) would give 1;
            # zero them so l stays 0 and the output row is exactly 0
            alive = m_new > jnp.float32(_NEG_INF / 2)
            p = jnp.where(alive, jnp.exp(sc - m_new), 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[:] = m_new

        @pl.when(ki == n_k - 1)
        def _finish():
            l = l_scr[:]
            lsafe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc_scr[:] / lsafe).astype(o_ref.dtype)
            # dead rows keep lse = _NEG_INF: the bwd kernels key off it
            lse = jnp.where(l == 0.0, jnp.float32(_NEG_INF),
                            m_scr[:] + jnp.log(lsafe))
            lse_ref[0, 0] = lse[:, 0]

    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, tk, d)
    v3 = v.reshape(b * h, tk, d)

    def qix(bh, qi, ki):
        del ki
        return (bh, qi, jnp.int32(0))

    def kix(bh, qi, ki):
        del qi
        return (bh, ki, jnp.int32(0))

    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((b,), lambda *_: (jnp.int32(0),),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), qix),
            pl.BlockSpec((1, bk, d), kix),
            pl.BlockSpec((1, bk, d), kix),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), qix),
            # (B*H, 1, T) so the block's last two dims are (1, 128): the
            # TPU lowering rejects a (1, 128) block over a 2D (B*H, T) array
            pl.BlockSpec((1, 1, bq), lambda bh, qi, ki: (bh, jnp.int32(0), qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_INTERPRET,
    )(kvalid, q3, k3, v3)
    return out.reshape(b, h, tq, d), lse.reshape(b * h, tq)


def _flash_bwd_dq(q, k, v, g, lse, delta, kvalid, causal, causal_off, scale, bq, bk):
    """dq on padded shapes: one pass over K blocks per Q block."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    n_k = tk // bk

    def kernel(vl_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, dl_ref,
               dq_ref, dq_scr):
        qi, ki = pl.program_id(1), pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            dq_scr[:] = jnp.zeros_like(dq_scr)

        # whole (B,) lengths vector lives in SMEM; pick this program's batch
        kvalid = vl_ref[jax.lax.div(pl.program_id(0), jnp.int32(h))]
        run = ki * bk < kvalid
        if causal:
            run = jnp.logical_and(run, ki * bk <= qi * bq + bq - 1 + causal_off)

        @pl.when(run)
        def _body():
            qb = q_ref[0].astype(jnp.float32) * jnp.float32(scale)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            gb = g_ref[0].astype(jnp.float32)
            sc = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            sc = _score_mask(sc, qi, ki, kvalid, causal, causal_off, bq, bk)
            lse_row = lse_ref[0, 0][:, None]
            p = jnp.where(lse_row > jnp.float32(_NEG_INF / 2),
                          jnp.exp(sc - lse_row), 0.0)
            dp = jax.lax.dot_general(
                gb, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - dl_ref[0, 0][:, None])
            dq_scr[:] += jax.lax.dot_general(
                ds, kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)

        @pl.when(ki == n_k - 1)
        def _finish():
            dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)

    def qix(bh, qi, ki):
        del ki
        return (bh, qi, jnp.int32(0))

    def kix(bh, qi, ki):
        del qi
        return (bh, ki, jnp.int32(0))

    def rix(bh, qi, ki):
        del ki
        return (bh, jnp.int32(0), qi)

    dq = pl.pallas_call(
        kernel,
        grid=(b * h, tq // bq, n_k),
        in_specs=[
            pl.BlockSpec((b,), lambda *_: (jnp.int32(0),),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), qix),
            pl.BlockSpec((1, bk, d), kix),
            pl.BlockSpec((1, bk, d), kix),
            pl.BlockSpec((1, bq, d), qix),
            pl.BlockSpec((1, 1, bq), rix),
            pl.BlockSpec((1, 1, bq), rix),
        ],
        out_specs=pl.BlockSpec((1, bq, d), qix),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_INTERPRET,
    )(kvalid, q.reshape(b * h, tq, d), k.reshape(b * h, tk, d),
      v.reshape(b * h, tk, d), g.reshape(b * h, tq, d),
      lse.reshape(b * h, 1, tq), delta.reshape(b * h, 1, tq))
    return dq.reshape(b, h, tq, d)


def _flash_bwd_dkv(q, k, v, g, lse, delta, kvalid, causal, causal_off, scale, bq, bk):
    """dk, dv on padded shapes: one pass over Q blocks per K block."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    n_q = tq // bq

    def kernel(vl_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, dl_ref,
               dk_ref, dv_ref, dk_scr, dv_scr):
        ki, qi = pl.program_id(1), pl.program_id(2)

        @pl.when(qi == 0)
        def _init():
            dk_scr[:] = jnp.zeros_like(dk_scr)
            dv_scr[:] = jnp.zeros_like(dv_scr)

        kvalid = vl_ref[jax.lax.div(pl.program_id(0), jnp.int32(h))]
        run = ki * bk < kvalid
        if causal:
            run = jnp.logical_and(run, qi * bq + bq - 1 >= ki * bk - causal_off)

        @pl.when(run)
        def _body():
            qb = q_ref[0].astype(jnp.float32) * jnp.float32(scale)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            gb = g_ref[0].astype(jnp.float32)
            sc = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            sc = _score_mask(sc, qi, ki, kvalid, causal, causal_off, bq, bk)
            lse_row = lse_ref[0, 0][:, None]
            p = jnp.where(lse_row > jnp.float32(_NEG_INF / 2),
                          jnp.exp(sc - lse_row), 0.0)
            dv_scr[:] += jax.lax.dot_general(
                p, gb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                gb, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - dl_ref[0, 0][:, None])
            dk_scr[:] += jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(qi == n_q - 1)
        def _finish():
            dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)

    def qix(bh, ki, qi):
        del ki
        return (bh, qi, jnp.int32(0))

    def kix(bh, ki, qi):
        del qi
        return (bh, ki, jnp.int32(0))

    def rix(bh, ki, qi):
        del ki
        return (bh, jnp.int32(0), qi)

    dk, dv = pl.pallas_call(
        kernel,
        grid=(b * h, tk // bk, n_q),
        in_specs=[
            pl.BlockSpec((b,), lambda *_: (jnp.int32(0),),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), qix),
            pl.BlockSpec((1, bk, d), kix),
            pl.BlockSpec((1, bk, d), kix),
            pl.BlockSpec((1, bq, d), qix),
            pl.BlockSpec((1, 1, bq), rix),
            pl.BlockSpec((1, 1, bq), rix),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), kix),
            pl.BlockSpec((1, bk, d), kix),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_INTERPRET,
    )(kvalid, q.reshape(b * h, tq, d), k.reshape(b * h, tk, d),
      v.reshape(b * h, tk, d), g.reshape(b * h, tq, d),
      lse.reshape(b * h, 1, tq), delta.reshape(b * h, 1, tq))
    return dk.reshape(b, h, tk, d), dv.reshape(b, h, tk, d)


def _platform_of(x):
    """Platform the op will execute on: a concrete array's own device (an
    eager CPU array next to an idle TPU chip must NOT pick the TPU kernel);
    tracers have no devices — they lower for the default backend."""
    import jax

    try:
        return next(iter(x.devices())).platform
    except Exception:
        return jax.default_backend()


def _supports_pallas(q, k):
    if not (_INTERPRET or _platform_of(q) in ("tpu", "axon")):
        return False
    if q.ndim != 4 or q.shape[-1] > 256:
        return False
    if _INTERPRET:
        # CPU kernel tests: exercise the pallas path on small shapes (below
        # half a block the padded-T waste makes even the interpreter moot)
        return q.shape[2] * k.shape[2] >= (_BLOCK // 2) ** 2
    # On hardware the crossover is empirical (v5e, B64 H12 D64, fwd+bwd):
    # XLA wins 3.3x at T=128 (0.39 vs 1.27 ms) and still ~1.2x at T=512;
    # flash wins 1.5x at T=2048 (7.3 vs 10.8 ms) and its O(T^2)->O(T*block)
    # memory is what makes long context fit at all. Route to flash only
    # where it pays.
    return q.shape[2] * k.shape[2] > 1024 * 1024


# -- Pallas path (custom vjp over the flash kernels) ------------------------
# The path choice (pallas vs xla) depends only on trace-static facts
# (shapes, backend, mask presence), so it happens in attention() before the
# custom_vjp boundary; residuals stay pure JAX arrays.


@functools.partial(
    __import__("jax").custom_vjp, nondiff_argnums=(4, 5)
)
def _flash_core(q, k, v, valid_length, causal, scale):
    out, _ = _flash_core_fwd(q, k, v, valid_length, causal, scale)
    return out


def _flash_core_fwd(q, k, v, valid_length, causal, scale):
    b, h, tq, d = q.shape
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    bq = _pick_block(tq, _MAX_BLOCK_FWD)
    bk = _pick_block(k.shape[2], _MAX_BLOCK_FWD)
    qp, kp, vp = _pad_qkv(q, k, v, bq, bk)
    kvalid = _kvalid_array(valid_length, b, k.shape[2])
    # causal offset from UNPADDED lengths: padded tq/tk shift the diagonal
    causal_off = k.shape[2] - tq
    outp, lse = _flash_fwd(qp, kp, vp, kvalid, causal, causal_off, s, bq, bk)
    out = outp[:, :, :tq, :d]
    # q/k/v saved unpadded: bwd re-pads (cheap) and shapes stay recoverable
    return out, (q, k, v, lse, kvalid, outp)


def _flash_core_bwd(causal, scale, res, g):
    import jax.numpy as jnp

    q, k, v, lse, kvalid, outp = res
    b, h, tq, d = q.shape
    tk = k.shape[2]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # bwd re-picks (smaller) blocks: it keeps 3 score-sized tiles in VMEM.
    # Its padded Tq never exceeds the fwd padding, so lse/out just slice.
    bq = _pick_block(tq, _MAX_BLOCK_BWD)
    bk = _pick_block(tk, _MAX_BLOCK_BWD)
    qp, kp, vp = _pad_qkv(q, k, v, bq, bk)
    tqp, dp = qp.shape[2], qp.shape[3]
    gp = jnp.pad(g, ((0, 0), (0, 0), (0, tqp - tq), (0, dp - d)))
    lse_b = lse[:, :tqp]
    outp_b = outp[:, :, :tqp, :]
    # delta_i = rowsum(dO_i * O_i): cheap elementwise reduce in XLA
    delta = jnp.sum(gp.astype(jnp.float32) * outp_b.astype(jnp.float32),
                    axis=-1).reshape(b * h, tqp)
    causal_off = tk - tq
    dq = _flash_bwd_dq(qp, kp, vp, gp.astype(qp.dtype), lse_b, delta,
                       kvalid, causal, causal_off, s, bq, bk)
    dk, dv = _flash_bwd_dkv(qp, kp, vp, gp.astype(qp.dtype), lse_b, delta,
                            kvalid, causal, causal_off, s, bq, bk)
    return (dq[:, :, :tq, :d], dk[:, :, :tk, :d], dv[:, :, :tk, :d], None)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


# -- XLA fallback path (recompute-in-backward to match flash memory) --------


@functools.partial(
    __import__("jax").custom_vjp, nondiff_argnums=(5, 6)
)
def _xla_core(q, k, v, mask, valid_length, causal, scale):
    return _reference_attention(q, k, v, mask, causal=causal, scale=scale,
                                valid_length=valid_length)


def _xla_core_fwd(q, k, v, mask, valid_length, causal, scale):
    out = _reference_attention(q, k, v, mask, causal=causal, scale=scale,
                               valid_length=valid_length)
    return out, (q, k, v, mask, valid_length)


def _xla_core_bwd(causal, scale, res, g):
    import jax

    q, k, v, mask, valid_length = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference_attention(
            q_, k_, v_, mask, causal, scale, valid_length=valid_length),
        q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None, None


_xla_core.defvjp(_xla_core_fwd, _xla_core_bwd)


def attention(q, k, v, mask=None, causal=False, scale=None, use_flash=True,
              valid_length=None):
    """Public entry: (B, H, T, D) scaled-dot-product attention.

    ``valid_length`` — (B,) int key lengths; the flash path masks in-kernel
    without materializing a (T, T) mask. ``mask`` — arbitrary dense boolean
    mask, broadcastable against (B, H, Tq, Tk); forces the XLA path.
    """
    global _LAST_PATH
    want_flash = use_flash and _FORCE_PATH != "xla" and (
        _supports_pallas(q, k)
        or (_FORCE_PATH == "pallas" and q.ndim == 4
            and q.shape[-1] <= 256))
    if mask is None and want_flash:
        _LAST_PATH = "pallas"
        return _flash_core(q, k, v, valid_length, causal, scale)
    _LAST_PATH = "xla"
    return _xla_core(q, k, v, mask, valid_length, causal, scale)
