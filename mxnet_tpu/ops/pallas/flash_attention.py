"""Flash attention: Pallas TPU kernel + XLA reference fallback.

The reference framework has no attention op at all — only fused matmul
helpers (``src/operator/contrib/transformer.cc``); SURVEY.md §5 requires the
TPU build to introduce memory-efficient attention natively.

Design (standard flash-attention-2 schedule adapted to TPU tiling):
  grid over (batch*heads, q_blocks, k_blocks); K/V blocks stream from HBM
  through VMEM with running max/sum accumulators in fp32 scratch.
Backward currently recomputes through the XLA path via ``jax.custom_vjp``
(numerically identical, still fused by XLA); a Pallas backward kernel is the
next optimization step.
"""
from __future__ import annotations

import functools
import math


def _reference_attention(q, k, v, mask=None, causal=False, scale=None):
    """XLA attention: materializes scores; fine for short T, CPU tests."""
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(cm, scores, -1e30)
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, -1e30)
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def _flash_attention_tpu(q, k, v, causal=False, scale=None,
                         block_q=128, block_k=128):
    """Pallas flash-attention forward for (B, H, T, D) inputs."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    n_q = tq // block_q
    n_k = tk // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr):
        qi = pl.program_id(1)

        @pl.when(pl.program_id(2) == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        ki = pl.program_id(2)

        run = True
        if causal:
            # skip fully-masked K blocks above the diagonal
            run = (ki * block_k) <= (qi * block_q + block_q - 1)

        @pl.when(run if causal else True)
        def _body():
            qb = q_ref[0].astype(jnp.float32) * s           # (bq, d)
            kb = k_ref[0].astype(jnp.float32)               # (bk, d)
            vb = v_ref[0].astype(jnp.float32)               # (bk, d)
            sc = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # (bq, bk)
            if causal:
                qpos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                kpos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                sc = jnp.where(qpos >= kpos, sc, -jnp.inf)
            m_prev = m_scr[:]                                # (bq, 1)
            m_cur = jnp.max(sc, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(sc - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[:] = m_new

        @pl.when(pl.program_id(2) == n_k - 1)
        def _finish():
            l = l_scr[:]
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)

    grid = (b * h, n_q, n_k)

    def qidx(bh, qi, ki):  # noqa: ANN001
        del ki
        return (bh, qi, 0)

    def kidx(bh, qi, ki):
        del qi
        return (bh, ki, 0)

    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, tk, d)
    v3 = v.reshape(b * h, tk, d)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), qidx),
            pl.BlockSpec((1, block_k, d), kidx),
            pl.BlockSpec((1, block_k, d), kidx),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), qidx),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(q3, k3, v3)
    return out.reshape(b, h, tq, d)


def _supports_pallas(q, causal_ok=True):  # pylint: disable=unused-argument
    import jax

    if jax.default_backend() not in ("tpu",):
        return False
    b, h, t, d = q.shape
    return t % 128 == 0 and d % 128 == 0 and d <= 256


@functools.partial(
    __import__("jax").custom_vjp, nondiff_argnums=(4, 5, 6)
)
def _attention_core(q, k, v, mask, causal, scale, use_flash):
    if mask is None and use_flash and _supports_pallas(q):
        return _flash_attention_tpu(q, k, v, causal=causal, scale=scale)
    return _reference_attention(q, k, v, mask, causal=causal, scale=scale)


def _attention_fwd(q, k, v, mask, causal, scale, use_flash):
    out = _attention_core(q, k, v, mask, causal, scale, use_flash)
    return out, (q, k, v, mask)


def _attention_bwd(causal, scale, use_flash, res, g):  # pylint: disable=unused-argument
    import jax

    q, k, v, mask = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference_attention(q_, k_, v_, mask, causal, scale),
        q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_attention_core.defvjp(_attention_fwd, _attention_bwd)


def attention(q, k, v, mask=None, causal=False, scale=None, use_flash=True):
    """Public entry: (B, H, T, D) scaled-dot-product attention."""
    return _attention_core(q, k, v, mask, causal, scale, use_flash)
