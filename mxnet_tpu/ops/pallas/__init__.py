"""Pallas TPU kernels for fusion-critical ops (SURVEY.md §7: attention,
normalization, optimizer fusions). Each kernel has an XLA fallback so the
same op runs on the CPU test mesh."""
from __future__ import annotations
