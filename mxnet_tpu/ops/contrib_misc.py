"""Contrib tail ops (VERDICT r3 item 6): the reference's niche
``_contrib_*`` kernels, implemented where they map cleanly onto XLA and
refused-with-guidance where they don't.

Implemented here:

* ``quadratic`` — the reference's tutorial op
  (``src/operator/contrib/quadratic_op-inl.h``): a·x² + b·x + c.
* ``gradientmultiplier`` — identity forward, grad × scalar backward
  (``src/operator/contrib/gradient_multiplier_op.cc``); the
  gradient-reversal-layer building block (scalar = -λ).
* ``count_sketch`` — random-projection sketch
  (``src/operator/contrib/count_sketch-inl.h``): one scatter-add, which
  is XLA-native; backward (a gather) comes from autodiff instead of the
  hand-written CUDA backward.
* ``hawkes_ll`` — marked-Hawkes-process log-likelihood
  (``src/operator/contrib/hawkes_ll-inl.h``): the per-event recurrence
  becomes a ``lax.scan`` over the sequence with one-hot mark updates
  (K marks live in registers; no serialized scatter), vmapped over the
  batch; the reference's hand-written backward kernel is replaced by
  autodiff through the scan.

Refused (see ``NOT_SUPPORTED`` in ``ops/legacy.py`` + ``nd.contrib``):
DGL graph-sampling family (data-dependent output shapes — host-side
graph preprocessing is the TPU-correct place), intgemm (x86 VNNI
intrinsics; the TPU int8 path is ``contrib/quantization``).
"""
from __future__ import annotations

from .registry import apply as _apply
from .registry import register as _register


def _jnp():
    import jax.numpy as jnp

    return jnp


def quadratic(data, a=0.0, b=0.0, c=0.0):
    """a·data² + b·data + c (reference ``_contrib_quadratic``)."""

    def f(x):
        return a * x * x + b * x + c

    return _apply(f, (data,), name="quadratic")


_GRADMULT_FNS = {}  # scalar -> custom_vjp fn (stable identity for the
                    # eager jit cache; a fresh closure per call would key
                    # -miss forever and pin dead callables)


def gradientmultiplier(data, scalar=1.0):
    """Forward identity; backward multiplies the gradient by ``scalar``
    (reference ``_contrib_gradientmultiplier``). ``scalar=-1`` is the
    gradient reversal layer of domain-adversarial training."""
    import jax

    key = float(scalar)
    f = _GRADMULT_FNS.get(key)
    if f is None:
        @jax.custom_vjp
        def f(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_, ct):
            return (ct * key,)

        f.defvjp(fwd, bwd)
        _GRADMULT_FNS[key] = f
    return _apply(f, (data,), name="gradientmultiplier")


def count_sketch(data, h, s, out_dim, processing_batch_size=32):  # pylint: disable=unused-argument
    """Count sketch projection (reference ``_contrib_count_sketch``):
    ``out[n, h[i]] += s[i] * data[n, i]`` over the flattened-to-2D input.
    ``processing_batch_size`` is accepted for API parity (a CUDA-kernel
    chunking knob; XLA owns scheduling here)."""

    def f(x, hh, ss):
        jnp = _jnp()
        x2 = x.reshape(x.shape[0], -1)
        idx = hh.reshape(-1).astype(jnp.int32)
        sign = ss.reshape(-1).astype(x2.dtype)
        out = jnp.zeros((x2.shape[0], int(out_dim)), x2.dtype)
        return out.at[:, idx].add(sign[None, :] * x2)

    return _apply(f, (data, h, s), name="count_sketch")


def hawkes_ll(mu, alpha, beta, state, lags, marks, valid_length, max_time):
    """Log-likelihood of a marked self-exciting Hawkes process
    (reference ``_contrib_hawkesll``; kernel math in
    ``hawkes_ll-inl.h:113-189``).

    Shapes: mu (N,K), alpha (K,), beta (K,), state (N,K), lags (N,T),
    marks (N,T) int32, valid_length (N,), max_time (N,).
    Returns ``(loglike (N,), out_state (N,K))`` — the state advanced to
    ``max_time`` for minibatched long sequences, exactly the reference's
    two-output contract.
    """
    import jax

    def f(mu_, alpha_, beta_, state_, lags_, marks_, vl_, mt_):
        jnp = _jnp()
        k = mu_.shape[1]

        def one(mu_i, state_i, lags_i, marks_i, vl_i, mt_i):
            def step(carry, inp):
                ll, t, last, st = carry
                lag, mark, valid = inp
                onehot = jax.nn.one_hot(mark, k, dtype=mu_i.dtype)
                t_new = t + lag
                d = t_new - (last * onehot).sum()
                ed = jnp.exp(-(beta_ * onehot).sum() * d)
                a_m = (alpha_ * onehot).sum()
                b_m = (beta_ * onehot).sum()
                mu_m = (mu_i * onehot).sum()
                s_m = (st * onehot).sum()
                lda = mu_m + a_m * b_m * s_m * ed
                comp = mu_m * d + a_m * s_m * (1 - ed)
                # padding steps: mask lda to 1 so log() stays finite even
                # when mu is 0 on an unused mark (0 * -inf would NaN)
                lda = jnp.where(valid > 0, lda, 1.0)
                ll_new = ll + valid * (jnp.log(lda) - comp)
                st_new = jnp.where(valid * onehot > 0, 1 + st * ed, st)
                last_new = jnp.where(valid * onehot > 0, t_new, last)
                t_new = jnp.where(valid > 0, t_new, t)
                return (ll_new, t_new, last_new, st_new), None

            t0 = jnp.zeros((), mu_i.dtype)
            last0 = jnp.zeros((k,), mu_i.dtype)
            ll0 = jnp.zeros((), mu_i.dtype)
            valid = (jnp.arange(lags_i.shape[0]) < vl_i).astype(mu_i.dtype)
            (ll, _, last, st), _ = jax.lax.scan(
                step, (ll0, t0, last0, state_i),
                (lags_i, marks_i, valid))
            # remaining compensator to max_time + state decay
            # (hawkesll_forward_compensator)
            d = mt_i - last
            ed = jnp.exp(-beta_ * d)
            rem = mu_i * d + alpha_ * st * (1 - ed)
            return ll - rem.sum(), ed * st

        return jax.vmap(one)(mu_, state_, lags_, marks_, vl_, mt_)

    return _apply(f, (mu, alpha, beta, state, lags, marks, valid_length,
                      max_time), name="hawkes_ll")


for _name in ("quadratic", "gradientmultiplier", "count_sketch",
              "hawkes_ll"):
    _register(_name, globals()[_name], wrapper=True)
_register("hawkesll", hawkes_ll, wrapper=True)  # reference spelling
