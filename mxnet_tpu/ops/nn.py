"""Neural-network operators (the ``npx`` op family), TPU-first.

Reference: ``src/operator/nn/`` (31k LoC of hand-written CPU/cuDNN/oneDNN
kernels — convolution, fully_connected, batch_norm, pooling, softmax,
dropout, ...; e.g. ``fully_connected.cc:251`` registers ``_npx_fully_connected``).

TPU design: every op is a pure JAX function lowering to ``lax`` primitives —
XLA maps conv/matmul onto the MXU and fuses the elementwise epilogues, which
is the role cuDNN autotuning + pointwise fusion (``src/operator/fusion/``)
play in the reference. Layout is NCHW at the API (reference default) but
convolutions compute through XLA's layout-agnostic ``conv_general_dilated``
so the compiler picks the MXU-friendly internal layout.

All public functions accept NDArray (or raw jax arrays) and route through the
dispatch layer for autograd.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as _onp

from .. import autograd
from .. import random as _rng
from ..base import MXNetError
from .registry import apply as _apply
from .registry import register as _register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _lax():
    import jax.lax as lax

    return lax


def _tup(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------


def _j_relu(x):
    return _jnp().maximum(x, 0)


def _j_sigmoid(x):
    import jax

    return jax.nn.sigmoid(x)


def _j_softrelu(x):
    import jax

    return jax.nn.softplus(x)


def _j_softsign(x):
    return x / (1 + _jnp().abs(x))


_ACTS = {}


def _act_fn(name):
    import jax

    if not _ACTS:
        _ACTS.update(
            relu=_j_relu,
            sigmoid=_j_sigmoid,
            log_sigmoid=jax.nn.log_sigmoid,
            tanh=_jnp().tanh,
            softrelu=_j_softrelu,
            softsign=_j_softsign,
            silu=jax.nn.silu,
            swish=jax.nn.silu,
            mish=lambda x: x * _jnp().tanh(jax.nn.softplus(x)),
            gelu=jax.nn.gelu,
            gelu_tanh=lambda x: jax.nn.gelu(x, approximate=True),
            erf_gelu=lambda x: jax.nn.gelu(x, approximate=False),
            identity=lambda x: x,
        )
    try:
        return _ACTS[name]
    except KeyError:
        raise MXNetError(f"unknown activation {name!r}") from None


def activation(data, act_type="relu", **kwargs):  # pylint: disable=unused-argument
    fn = _act_fn(act_type)
    return _apply(fn, (data,), name=f"activation:{act_type}")


def relu(data):
    return _apply(_j_relu, (data,), name="relu")


def sigmoid(data):
    return _apply(_j_sigmoid, (data,), name="sigmoid")


def tanh(data):
    return _apply(_jnp().tanh, (data,), name="tanh")


def softsign(data):
    return _apply(_j_softsign, (data,), name="softsign")


def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, **kwargs):  # pylint: disable=unused-argument
    """LeakyReLU family (reference ``src/operator/leaky_relu.cc``)."""
    import jax

    jnp = _jnp()
    if act_type == "leaky":
        return _apply(lambda x: jnp.where(x >= 0, x, slope * x), (data,),
                      name="leaky_relu")
    if act_type == "elu":
        return _apply(lambda x: jax.nn.elu(x, alpha=slope), (data,), name="elu")
    if act_type == "selu":
        return _apply(jax.nn.selu, (data,), name="selu")
    if act_type == "gelu":
        return _apply(jax.nn.gelu, (data,), name="gelu")
    if act_type == "prelu":
        return _apply(lambda x, g: jnp.where(x >= 0, x, g * x), (data, gamma),
                      name="prelu")
    if act_type == "rrelu":
        if autograd.is_training():
            import jax.random as jr

            key = _rng.next_key()
            def f(x):
                s = jr.uniform(key, x.shape, x.dtype, lower_bound, upper_bound)
                return jnp.where(x >= 0, x, s * x)
            return _apply(f, (data,), name="rrelu")
        mid = (lower_bound + upper_bound) / 2
        return _apply(lambda x: jnp.where(x >= 0, x, mid * x), (data,), name="rrelu")
    raise MXNetError(f"unknown leaky_relu act_type {act_type!r}")


# ---------------------------------------------------------------------------
# softmax family (reference src/operator/nn/softmax.cc, log_softmax.cc)
# ---------------------------------------------------------------------------


def softmax(data, axis=-1, length=None, temperature=None, use_length=False, dtype=None):
    import jax

    jnp = _jnp()

    def f(x, *rest):
        xx = x if temperature in (None, 1.0) else x / temperature
        if use_length and rest:
            ln = rest[0]
            idx = jnp.arange(xx.shape[axis])
            shape = [1] * xx.ndim
            shape[axis] = xx.shape[axis]
            mask = idx.reshape(shape) < jnp.expand_dims(ln, axis=axis)
            xx = jnp.where(mask, xx, -jnp.inf)
            out = jax.nn.softmax(xx, axis=axis)
            out = jnp.where(mask, out, 0.0)
        else:
            out = jax.nn.softmax(xx, axis=axis)
        return out.astype(dtype) if dtype else out

    args = (data, length) if (use_length and length is not None) else (data,)
    return _apply(f, args, name="softmax")


def log_softmax(data, axis=-1, temperature=None, dtype=None, use_length=False, length=None):  # pylint: disable=unused-argument
    import jax

    def f(x):
        xx = x if temperature in (None, 1.0) else x / temperature
        out = jax.nn.log_softmax(xx, axis=axis)
        return out.astype(dtype) if dtype else out

    return _apply(f, (data,), name="log_softmax")


def masked_softmax(data, mask, axis=-1, temperature=1.0):
    import jax

    jnp = _jnp()

    def f(x, m):
        xx = x / temperature if temperature != 1.0 else x
        xx = jnp.where(m, xx, -1e30)
        out = jax.nn.softmax(xx, axis=axis)
        return jnp.where(m, out, 0.0)

    return _apply(f, (data, mask), name="masked_softmax")


def masked_log_softmax(data, mask, axis=-1, temperature=1.0):
    import jax

    jnp = _jnp()

    def f(x, m):
        xx = x / temperature if temperature != 1.0 else x
        xx = jnp.where(m, xx, -1e30)
        return jax.nn.log_softmax(xx, axis=axis)

    return _apply(f, (data, mask), name="masked_log_softmax")


# ---------------------------------------------------------------------------
# dense / conv / pooling
# ---------------------------------------------------------------------------


def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    """y = x @ W^T + b (reference ``src/operator/nn/fully_connected.cc``).

    ``flatten=True`` collapses all non-batch dims (reference semantics);
    ``flatten=False`` applies to the trailing dim.
    """
    jnp = _jnp()

    def f(xx, ww, *mb):
        if flatten and xx.ndim > 2:
            xx = xx.reshape(xx.shape[0], -1)
        out = jnp.matmul(xx, ww.T)
        if mb:
            out = out + mb[0]
        return out

    args = (x, weight) if (no_bias or bias is None) else (x, weight, bias)
    return _apply(f, args, name="fully_connected")


_CONV_LAYOUTS = {
    1: ("NCW", "OIW", "NCW"),
    2: ("NCHW", "OIHW", "NCHW"),
    3: ("NCDHW", "OIDHW", "NCDHW"),
}


def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                layout=None, **kwargs):  # pylint: disable=unused-argument
    """N-D convolution via ``lax.conv_general_dilated`` (MXU path).

    Reference: ``src/operator/nn/convolution.cc`` + cuDNN wrappers. XLA owns
    algorithm choice/layout; grouped conv maps to ``feature_group_count``.
    """
    lax = _lax()
    ksize = len(kernel) if kernel is not None else None

    def f(x, w, *mb):
        nd = x.ndim - 2
        lhs_spec, rhs_spec, out_spec = _CONV_LAYOUTS[nd]
        strides = _tup(stride, nd)
        dil = _tup(dilate, nd)
        pads = _tup(pad, nd) if pad is not None else (0,) * nd
        padding = [(p, p) for p in pads]
        out = lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dil, feature_group_count=num_group,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec),
        )
        if mb:
            b = mb[0].reshape((1, -1) + (1,) * nd)
            out = out + b
        return out

    del ksize
    args = (data, weight) if (no_bias or bias is None) else (data, weight, bias)
    return _apply(f, args, name="convolution")


def deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, num_filter=None, num_group=1,
                  no_bias=True, layout=None, target_shape=None, **kwargs):  # pylint: disable=unused-argument
    """Transposed convolution (reference ``src/operator/nn/deconvolution.cc``).

    Implemented as the gradient of convolution (``lax.conv_transpose`` with
    IOW-spec weights), matching the reference's definition.
    """
    lax = _lax()

    def f(x, w, *mb):
        nd = x.ndim - 2
        strides = _tup(stride, nd)
        dil = _tup(dilate, nd)
        pads = _tup(pad, nd) if pad is not None else (0,) * nd
        adjs = _tup(adj, nd) if adj is not None else (0,) * nd
        # output padding handled by asymmetric padding on the transpose
        padding = []
        kernel_shape = w.shape[2:]
        for i in range(nd):
            k = (kernel_shape[i] - 1) * dil[i] + 1
            lo = k - 1 - pads[i]
            hi = k - 1 - pads[i] + adjs[i]
            padding.append((lo, hi))
        lhs_spec, rhs_spec, out_spec = _CONV_LAYOUTS[nd]
        # IOW-style spec: swap I/O in rhs for transpose semantics
        rhs_spec_t = rhs_spec.replace("O", "X").replace("I", "O").replace("X", "I")
        out = lax.conv_general_dilated(
            x, _jnp().flip(w, axis=tuple(range(2, w.ndim))),
            window_strides=(1,) * nd, padding=padding,
            lhs_dilation=strides, rhs_dilation=dil,
            feature_group_count=num_group,
            dimension_numbers=(lhs_spec, rhs_spec_t, out_spec),
        )
        if mb:
            out = out + mb[0].reshape((1, -1) + (1,) * nd)
        return out

    args = (data, weight) if (no_bias or bias is None) else (data, weight, bias)
    return _apply(f, args, name="deconvolution")


def pooling(data, kernel=None, pool_type="max", global_pool=False, stride=None,
            pad=None, pooling_convention="valid", count_include_pad=True,
            layout=None, **kwargs):  # pylint: disable=unused-argument
    """Pooling via ``lax.reduce_window`` (reference ``src/operator/nn/pooling.cc``)."""
    lax = _lax()
    jnp = _jnp()

    def f(x):
        nd = x.ndim - 2
        if global_pool:
            axes = tuple(range(2, x.ndim))
            if pool_type == "max":
                return jnp.max(x, axis=axes, keepdims=True)
            if pool_type == "sum":
                return jnp.sum(x, axis=axes, keepdims=True)
            return jnp.mean(x, axis=axes, keepdims=True)
        ker = _tup(kernel, nd)
        strides = _tup(stride, nd) if stride is not None else ker
        pads = _tup(pad, nd) if pad is not None else (0,) * nd
        window = (1, 1) + ker
        wstrides = (1, 1) + strides
        if pooling_convention == "full":
            # ceil-mode: pad high side enough to cover a final partial window
            wpad = [(0, 0), (0, 0)]
            for i in range(nd):
                size = x.shape[2 + i] + 2 * pads[i]
                out_f = max(0, math.ceil((size - ker[i]) / strides[i])) + 1
                needed = (out_f - 1) * strides[i] + ker[i] - size
                wpad.append((pads[i], pads[i] + max(0, needed)))
        else:
            wpad = [(0, 0), (0, 0)] + [(p, p) for p in pads]
        if pool_type == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            return lax.reduce_window(x, init, lax.max, window, wstrides, wpad)
        if pool_type in ("avg", "sum"):
            s = lax.reduce_window(x, 0.0, lax.add, window, wstrides, wpad)
            if pool_type == "sum":
                return s
            if count_include_pad:
                denom = float(_onp.prod(ker))
                return s / denom
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, wstrides, wpad)
            return s / cnt
        if pool_type == "lp":
            p = kwargs.get("p_value", 2)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, wstrides, wpad)
            return s ** (1.0 / p)
        raise MXNetError(f"unknown pool_type {pool_type!r}")

    return _apply(f, (data,), name=f"pooling:{pool_type}")


def adaptive_avg_pooling(data, output_size=1):
    """``_contrib_AdaptiveAvgPooling2D`` analog."""
    jnp = _jnp()
    if isinstance(output_size, int):
        output_size = (output_size, output_size)

    def f(x):
        n, c, h, w = x.shape
        oh, ow = output_size
        if h % oh == 0 and w % ow == 0:
            x4 = x.reshape(n, c, oh, h // oh, ow, w // ow)
            return x4.mean(axis=(3, 5))
        import jax

        x_resized = jax.image.resize(x, (n, c, oh, ow), method="linear")
        return x_resized

    return _apply(f, (data,), name="adaptive_avg_pooling")


# ---------------------------------------------------------------------------
# normalization (reference src/operator/nn/{batch_norm,layer_norm,...}.cc)
# ---------------------------------------------------------------------------


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, **kwargs):  # pylint: disable=unused-argument
    """Batch normalization.

    Training mode (autograd.is_training() and not use_global_stats): uses
    batch statistics and returns updated running stats via the layer (see
    ``gluon.nn.BatchNorm`` which rebinds its state params — the reference
    mutates aux states inside the op instead).
    """
    jnp = _jnp()
    training = autograd.is_training() and not use_global_stats

    def f_train(xx, g, b):
        axes = tuple(i for i in range(xx.ndim) if i != axis)
        if jnp.dtype(xx.dtype).itemsize <= 2:
            # bf16/fp16 AMP path: one-pass fp32 stats (E[x], E[x^2]).
            # jnp.var's two-pass form costs an extra HBM sweep of the
            # activation per BN — measured ~6% of the whole ResNet-50 train
            # step on v5e (BN fusions run at the HBM roofline, see
            # profiler.device_op_table). fp32 accumulation is strictly more
            # accurate than two-pass arithmetic in the input's own 16-bit
            # dtype; the clamp guards E[x^2]-E[x]^2 cancellation.
            x32 = xx.astype(jnp.float32)
            mean32 = jnp.mean(x32, axis=axes)
            var32 = jnp.maximum(
                jnp.mean(jnp.square(x32), axis=axes) - jnp.square(mean32),
                0.0)
            # stats stay fp32: they feed the running-stat update, and the
            # reference keeps BN aux states fp32 under AMP — only the
            # normalization arithmetic below casts down
            mean, var = mean32, var32
            inv_c = 1.0 / jnp.sqrt(var32 + eps)
        else:
            # fp32/fp64: keep the exact two-pass form — one-pass
            # cancellation at |mean| >> std would be a precision regression
            # with no bandwidth story (full-precision nets are not the
            # perf-critical path)
            mean = jnp.mean(xx, axis=axes)
            var = jnp.var(xx, axis=axes)
            inv_c = 1.0 / jnp.sqrt(var + eps)
        shape = [1] * xx.ndim
        shape[axis] = xx.shape[axis]
        gg = jnp.ones_like(g) if fix_gamma else g
        inv = (gg.astype(inv_c.dtype) * inv_c).astype(xx.dtype).reshape(shape)
        out = ((xx - mean.astype(xx.dtype).reshape(shape)) * inv
               + b.reshape(shape))
        return out, mean, var

    def f_eval(xx, g, b, rm, rv):
        shape = [1] * xx.ndim
        shape[axis] = xx.shape[axis]
        gg = jnp.ones_like(g) if fix_gamma else g
        inv = gg.reshape(shape) / jnp.sqrt(rv.reshape(shape) + eps)
        return (xx - rm.reshape(shape)) * inv + b.reshape(shape)

    if training:
        out, mean, var = _apply(f_train, (x, gamma, beta), name="batch_norm")
        # state update is the caller's job (the layer folds batch stats into
        # its running_* parameters), so stats are only returned on request
        if output_mean_var:
            return out, mean, var
        return out
    out = _apply(f_eval, (x, gamma, beta, running_mean, running_var),
                 name="batch_norm_inference")
    if output_mean_var:
        return out, running_mean, running_var
    return out


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    jnp = _jnp()

    def f(x, g, b):
        mean = jnp.mean(x, axis=axis, keepdims=True)
        var = jnp.var(x, axis=axis, keepdims=True)
        out = (x - mean) / jnp.sqrt(var + eps)
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        return out * g.reshape(shape) + b.reshape(shape)

    return _apply(f, (data, gamma, beta), name="layer_norm")


def rms_norm(data, gamma, axis=-1, eps=1e-6):
    """RMSNorm (no reference analog; required by the Llama model family)."""
    jnp = _jnp()

    def f(x, g):
        ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axis, keepdims=True)
        out = x * (1.0 / jnp.sqrt(ms + eps)).astype(x.dtype)
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        return out * g.reshape(shape)

    return _apply(f, (data, gamma), name="rms_norm")


def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    jnp = _jnp()

    def f(x, g, b):
        n, c = x.shape[:2]
        rest = x.shape[2:]
        xg = x.reshape((n, num_groups, c // num_groups) + rest)
        axes = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        out = ((xg - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
        shape = (1, c) + (1,) * len(rest)
        return out * g.reshape(shape) + b.reshape(shape)

    return _apply(f, (data, gamma, beta), name="group_norm")


def instance_norm(data, gamma, beta, eps=1e-5):
    jnp = _jnp()

    def f(x, g, b):
        axes = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        out = (x - mean) / jnp.sqrt(var + eps)
        shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
        return out * g.reshape(shape) + b.reshape(shape)

    return _apply(f, (data, gamma, beta), name="instance_norm")


def l2_normalization(data, eps=1e-10, mode="instance"):
    jnp = _jnp()

    def f(x):
        if mode == "instance":
            axes = tuple(range(1, x.ndim))
        elif mode == "channel":
            axes = (1,)
        else:
            axes = tuple(range(x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
        return x / norm

    return _apply(f, (data,), name="l2_normalization")


# ---------------------------------------------------------------------------
# dropout (reference src/operator/nn/dropout.cc; RNG via engine resource)
# ---------------------------------------------------------------------------


def dropout(data, p=0.5, mode="training", axes=(), **kwargs):  # pylint: disable=unused-argument
    if p <= 0 or (mode == "training" and not autograd.is_training()):
        return data if hasattr(data, "_data") else data
    import jax.random as jr

    jnp = _jnp()
    key = _rng.next_key()

    def f(x):
        shape = list(x.shape)
        for ax in axes:
            shape[ax] = 1
        keep = 1.0 - p
        mask = jr.bernoulli(key, keep, tuple(shape)).astype(x.dtype)
        return x * mask / keep

    return _apply(f, (data,), name="dropout")


# ---------------------------------------------------------------------------
# embedding / one-hot / indexing ops
# ---------------------------------------------------------------------------


def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False, **kwargs):  # pylint: disable=unused-argument
    """Embedding lookup (reference ``src/operator/tensor/indexing_op.cc``).

    ``sparse_grad=True``: the weight's gradient is produced as a
    ``RowSparseNDArray`` holding only the touched rows (unique indices,
    duplicate contributions segment-summed) — O(nnz) end to end, the
    reference's ``SparseEmbedding`` backward contract. Sparse production
    needs concrete indices, so inside a jit/hybridize trace the dense
    gradient path is used instead.
    """
    jnp = _jnp()

    def f(idx, w):
        return jnp.take(w, idx.astype(jnp.int32), axis=0)

    if sparse_grad and autograd.is_recording() and not _rng.in_trace():
        import jax

        from ..ndarray.ndarray import NDArray, _slot_of, _tracked
        from ..ndarray.sparse import RowSparseNDArray, _unique_static

        idx_nd = data if isinstance(data, NDArray) else NDArray(data)
        w_nd = weight if isinstance(weight, NDArray) else NDArray(weight)
        if isinstance(idx_nd._data, jax.core.Tracer) \
                or isinstance(w_nd._data, jax.core.Tracer):
            return _apply(f, (data, weight), name="embedding")
        out_data = f(idx_nd._data, w_nd._data)
        out = NDArray(out_data)
        if _tracked(w_nd):
            idx_flat = idx_nd._data.reshape(-1).astype(jnp.int64)
            vocab, dim = w_nd.shape
            uniq, inv = _unique_static(idx_flat)

            def vjp_fn(ct, _u=uniq, _i=inv, _v=vocab, _d=dim):
                ctf = ct.reshape(-1, _d)
                vals = jnp.zeros((_u.shape[0], _d),
                                 ctf.dtype).at[_i].add(ctf)
                return (None,
                        RowSparseNDArray(NDArray(vals), NDArray(_u),
                                         (_v, _d)))

            node = autograd.TapeNode(
                vjp_fn, [_slot_of(idx_nd), _slot_of(w_nd)],
                [(out.shape, out.dtype)], name="embedding_sparse")
            out._tape = (node, 0)
        return out

    return _apply(f, (data, weight), name="embedding")


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    import jax

    def f(idx):
        oh = jax.nn.one_hot(idx, depth, dtype=dtype)
        if on_value != 1.0 or off_value != 0.0:
            oh = oh * (on_value - off_value) + off_value
        return oh

    return _apply(f, (data,), name="one_hot", record=False)


def pick(data, index, axis=-1, keepdims=False, mode="clip"):  # pylint: disable=unused-argument
    """Pick per-row elements by index (reference ``pick`` op)."""
    jnp = _jnp()

    def f(x, idx):
        out = jnp.take_along_axis(
            x, jnp.expand_dims(idx.astype(jnp.int32), axis=axis), axis=axis)
        return out if keepdims else jnp.squeeze(out, axis=axis)

    return _apply(f, (data, index), name="pick")


def gather_nd(data, indices):
    jnp = _jnp()

    def f(x, idx):
        idx = idx.astype(jnp.int32)
        return x[tuple(idx[i] for i in range(idx.shape[0]))]

    return _apply(f, (data, indices), name="gather_nd")


def scatter_nd(data, indices, shape):
    jnp = _jnp()

    def f(v, idx):
        idx = idx.astype(jnp.int32)
        out = jnp.zeros(shape, v.dtype)
        return out.at[tuple(idx[i] for i in range(idx.shape[0]))].set(v)

    return _apply(f, (data, indices), name="scatter_nd")


def topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Top-k (reference ``src/operator/tensor/ordering_op.cc``)."""
    import jax

    jnp = _jnp()

    def f(x):
        xm = jnp.moveaxis(x, axis, -1)
        vals, idx = jax.lax.top_k(-xm if is_ascend else xm, k)
        if is_ascend:
            vals = -vals
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
        if ret_typ == "value":
            return vals
        if ret_typ == "both":
            return vals, idx.astype(dtype)
        return idx.astype(dtype)

    return _apply(f, (data,), name="topk", record=(ret_typ == "value"))


# ---------------------------------------------------------------------------
# sequence ops (reference src/operator/sequence_*.cc)
# ---------------------------------------------------------------------------


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    jnp = _jnp()
    if not use_sequence_length or sequence_length is None:
        return data

    def f(x, slen):
        idx = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        batch_axis = 1 if axis == 0 else 0
        bshape = [1] * x.ndim
        bshape[batch_axis] = x.shape[batch_axis]
        mask = idx.reshape(shape) < slen.reshape(bshape)
        return jnp.where(mask, x, value)

    return _apply(f, (data, sequence_length), name="sequence_mask")


def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    jnp = _jnp()

    def f(x, *rest):
        if rest:
            idx = (rest[0].astype(jnp.int32) - 1)
            return jnp.take_along_axis(
                x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)), axis=axis
            ).squeeze(axis)
        return jnp.take(x, x.shape[axis] - 1, axis=axis)

    args = (data, sequence_length) if (use_sequence_length and sequence_length is not None) else (data,)
    return _apply(f, args, name="sequence_last")


def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    jnp = _jnp()

    def f(x, *rest):
        if not rest:
            return jnp.flip(x, axis=axis)
        slen = rest[0].astype(jnp.int32)
        t = x.shape[axis]
        idx = jnp.arange(t)
        rev = slen[None, :] - 1 - idx[:, None]
        rev = jnp.where(rev >= 0, rev, idx[:, None])
        return jnp.take_along_axis(x, rev.reshape((t, -1) + (1,) * (x.ndim - 2)), axis=0)

    args = (data, sequence_length) if (use_sequence_length and sequence_length is not None) else (data,)
    return _apply(f, args, name="sequence_reverse")


# ---------------------------------------------------------------------------
# losses as ops
# ---------------------------------------------------------------------------


def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    """CTC loss (reference ``src/operator/nn/ctc_loss.cc`` / WarpCTC).

    Lowered through optax's ctc_loss (pure-JAX forward-backward) with
    logit layout conversion: reference layout is (seq, batch, alphabet).
    """
    import optax

    jnp = _jnp()

    def f(logits, labels, *rest):
        sl, b, a = logits.shape
        lg = jnp.transpose(logits, (1, 0, 2))  # (B, T, A)
        lab = labels.astype(jnp.int32)
        if blank_label == "first":
            # optax uses blank=0 by default; reference 'first' means blank==0
            blank_id = 0
        else:
            blank_id = a - 1
        if rest and use_data_lengths:
            dl = rest[0].astype(jnp.int32)
        else:
            dl = jnp.full((b,), sl, jnp.int32)
        logit_pad = (jnp.arange(sl)[None, :] >= dl[:, None]).astype(jnp.float32)
        if use_label_lengths and len(rest) > (1 if use_data_lengths else 0):
            ll = rest[-1].astype(jnp.int32)
        else:
            ll = jnp.sum((lab > 0).astype(jnp.int32), axis=-1)
        label_pad = (jnp.arange(lab.shape[1])[None, :] >= ll[:, None]).astype(jnp.float32)
        return optax.ctc_loss(lg, logit_pad, lab, label_pad, blank_id=blank_id)

    args = [data, label]
    if use_data_lengths and data_lengths is not None:
        args.append(data_lengths)
    if use_label_lengths and label_lengths is not None:
        args.append(label_lengths)
    return _apply(f, tuple(args), name="ctc_loss")


def smooth_l1(data, scalar=1.0):
    jnp = _jnp()

    def f(x):
        s2 = scalar * scalar
        return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * x * x,
                         jnp.abs(x) - 0.5 / s2)

    return _apply(f, (data,), name="smooth_l1")


# ---------------------------------------------------------------------------
# attention (TPU flagship path — Pallas flash attention with XLA fallback)
# ---------------------------------------------------------------------------


def attention(query, key, value, mask=None, causal=False, scale=None,
              use_flash=True, valid_length=None):
    """Scaled dot-product attention over (B, H, T, D) tensors.

    Replaces the reference's fused matmul helpers
    (``src/operator/contrib/transformer.cc`` interleaved_matmul_selfatt_*)
    with a real attention op: Pallas flash-attention kernel on TPU,
    XLA-fused reference path elsewhere. ``valid_length`` (B,) key lengths
    are masked inside the flash kernel (no dense mask materialized); a
    dense ``mask`` forces the XLA path. See
    ``mxnet_tpu/ops/pallas/flash_attention.py``.
    """
    from .pallas import flash_attention as fa

    n_extra = (mask is not None, valid_length is not None)
    # routing globals must live in f's CLOSURE: the eager jit cache keys
    # ops on (code, closure values), and a cached executable replays its
    # traced path — without these cells a force_path()/use_interpret()
    # flip would silently keep serving the previously-traced kernel
    routing = (fa._FORCE_PATH, fa._INTERPRET)

    def f(q, k, v, *extra):
        assert routing == (fa._FORCE_PATH, fa._INTERPRET)
        it = iter(extra)
        m = next(it) if n_extra[0] else None
        vl = next(it) if n_extra[1] else None
        return fa.attention(q, k, v, m, causal=causal, scale=scale,
                            use_flash=use_flash, valid_length=vl)

    args = (query, key, value)
    if mask is not None:
        args = args + (mask,)
    if valid_length is not None:
        args = args + (valid_length,)
    return _apply(f, args, name="attention")


# ---------------------------------------------------------------------------
# KV-cache serving ops (mxnet_tpu.serve)
#
# These four ops are the compute core of autoregressive decode. They are
# deliberately written in a *shape-stable* formulation: every reduction
# (score dot products, softmax statistics, the value-weighted sum) runs
# over the LAST axis of a tensor whose reduced extent is fixed by the
# cache length, never by the query length. On the XLA CPU/TPU backends
# this makes the per-position arithmetic bitwise identical whether the
# query block is a full prefill (T = bucket) or a single decode token
# (T = 1) — the property tests/test_serve.py asserts. A batched
# dot_general here would NOT have it (its tiling changes with T; measured
# ~1e-5 drift on CPU), which is why these do not reuse ``attention``.
# ---------------------------------------------------------------------------


def kv_cache_write(cache, new, start_pos):
    """Write ``new`` (B, H, T, D) into the ring ``cache`` (B, H, S, D) at
    per-row positions ``start_pos[b] + [0..T)``.

    Gather+select formulation (``take_along_axis`` + ``where``) instead of
    a scatter: deterministic, differentiable-free, and exact — selected
    elements are copied, not arithmetically merged, so ``-0.0`` and
    payload bits survive untouched.
    """

    def f(c, n, sp):
        jnp = _jnp()
        s_len = c.shape[2]
        t_len = n.shape[2]
        s_idx = jnp.arange(s_len, dtype=jnp.int32)[None, :]      # (1, S)
        sp_ = sp.astype(jnp.int32)[:, None]                      # (B, 1)
        in_window = (s_idx >= sp_) & (s_idx < sp_ + t_len)       # (B, S)
        src = jnp.clip(s_idx - sp_, 0, t_len - 1)                # (B, S)
        gathered = jnp.take_along_axis(n, src[:, None, :, None], axis=2)
        return jnp.where(in_window[:, None, :, None], gathered, c)

    return _apply(f, (cache, new, start_pos), name="kv_cache_write")


def kv_cache_write_q(cache_q, cache_scale, new, start_pos):
    """Quantize-on-write into an int8 KV ring: ``new`` (B, H, T, D) f32 is
    symmetric-quantized per token per head (scale = max|row| / 127 over D)
    and written into ``cache_q`` (B, H, S, D) int8 with its scale row into
    ``cache_scale`` (B, H, S) f32, at positions ``start_pos[b] + [0..T)``.

    Same gather+select window as ``kv_cache_write`` — untouched ring slots
    are copied, not merged. Returns ``(new_cache_q, new_cache_scale)``;
    dequantization happens inside ``cached_attention``'s fast path.
    """

    def f(cq, cs, n, sp):
        jnp = _jnp()
        s_len = cq.shape[2]
        t_len = n.shape[2]
        amax = jnp.max(jnp.abs(n), axis=-1)                      # (B, H, T)
        scale = jnp.maximum(amax / 127.0, 1e-8)
        nq = jnp.clip(jnp.round(n / scale[..., None]),
                      -127, 127).astype(jnp.int8)
        s_idx = jnp.arange(s_len, dtype=jnp.int32)[None, :]      # (1, S)
        sp_ = sp.astype(jnp.int32)[:, None]                      # (B, 1)
        in_window = (s_idx >= sp_) & (s_idx < sp_ + t_len)       # (B, S)
        src = jnp.clip(s_idx - sp_, 0, t_len - 1)                # (B, S)
        gq = jnp.take_along_axis(nq, src[:, None, :, None], axis=2)
        gs = jnp.take_along_axis(scale, src[:, None, :], axis=2)
        return (jnp.where(in_window[:, None, :, None], gq, cq),
                jnp.where(in_window[:, None, :], gs, cs))

    return _apply(f, (cache_q, cache_scale, new, start_pos),
                  name="kv_cache_write_q")


def quantized_dense(data, qweight, scale, bias=None):
    """int8 fully-connected: ``data`` (..., U) f32 against a pre-quantized
    ``qweight`` (O, U) int8 with per-output-channel ``scale`` (O,) f32.

    On TPU, activations are quantized dynamically per row (symmetric,
    max|x|/127 over U) so the inner product runs int8 x int8 -> int32 on
    the MXU's 394 TOP/s int8 units, then rescales to f32. XLA CPU has no
    int8 gemm worth using (the s8 dot lowers to a scalar loop — measured
    slower than the f32 path it replaces), so there the op is weight-only
    quantization: dequantize ``qweight`` inline and run the f32 gemm —
    weights still live at half size, activations stay f32. Serving
    fast-path only: ~1e-2 relative error vs the f32 gemm, covered by the
    tolerance parity suite, never by the bitwise contract.
    """
    import jax

    int8_dot = jax.default_backend() in ("tpu", "axon")

    def f(x, w, s, *b):
        import jax

        jnp = _jnp()
        if int8_dot:
            amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
            sx = jnp.maximum(amax / 127.0, 1e-8)
            xq = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, w, (((x.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * sx * s
        else:
            # per-output-channel scale is a column scale of the gemm, so
            # it commutes to the output: scaling (..., O) activations is
            # U-times cheaper than scaling the (O, U) weight, and the
            # int8->f32 convert fuses into the gemm's weight read
            out = jax.lax.dot_general(
                x, w.astype(jnp.float32),
                (((x.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * s
        return out + b[0] if b else out

    args = (data, qweight, scale)
    if bias is not None:
        args = args + (bias,)
    return _apply(f, args, name="quantized_dense")


def cached_attention(query, key, value, start_pos, scale=None,
                     path="baseline", k_scale=None, v_scale=None):
    """Causal attention of ``query`` (B, H, T, D) — absolute positions
    ``start_pos[b] + t`` — over a KV ring (B, H, S, D).

    Positions ``> start_pos[b] + t`` (future tokens, unwritten or padded
    ring slots) are masked to ``-inf`` before the softmax; their
    probabilities are exactly 0.0, so ring garbage contributes exact zeros
    to the value sum. See the section comment for why this is a
    mul+reduce, not a dot.

    ``path`` selects the formulation: "baseline" is the shape-stable
    mul+reduce above (the bitwise prefill/decode contract); any other
    value routes to the fused decode-attention kernel
    (``ops/pallas/decode_attention``), which takes *unexpanded* GQA K/V of
    shape (B, KV, S, D) — optionally int8 with (B, KV, S)
    ``k_scale``/``v_scale`` rings dequantized in-kernel — and carries a
    tolerance (not bitwise) parity contract.
    """
    d = query.shape[-1]
    sc = float(scale) if scale is not None else 1.0 / math.sqrt(d)

    if path != "baseline":
        from .pallas import decode_attention as da

        # routing globals must live in f's closure (see attention())
        routing = (da._FORCE_PATH, da._INTERPRET)
        has_scales = k_scale is not None

        def f(q, k, v, sp, *extra):
            assert routing == (da._FORCE_PATH, da._INTERPRET)
            ks, vs = (extra[0], extra[1]) if has_scales else (None, None)
            return da.decode_attention(q, k, v, sp, scale=sc,
                                       k_scale=ks, v_scale=vs)

        args = (query, key, value, start_pos)
        if has_scales:
            args = args + (k_scale, v_scale)
        return _apply(f, args, name="cached_attention_fast")

    def f(q, k, v, sp):
        jnp = _jnp()
        t_len = q.shape[2]
        s_len = k.shape[2]
        pos = sp.astype(jnp.int32)[:, None] \
            + jnp.arange(t_len, dtype=jnp.int32)[None, :]        # (B, T)
        valid = jnp.arange(s_len, dtype=jnp.int32)[None, None, :] \
            <= pos[:, :, None]                                   # (B, T, S)
        s = jnp.sum(q[:, :, :, None, :] * k[:, :, None, :, :],
                    axis=-1) * sc                                # (B, H, T, S)
        s = jnp.where(valid[:, None], s, -jnp.inf)
        m = jnp.max(s, axis=-1, keepdims=True)
        e = jnp.exp(s - m)
        p = e / jnp.sum(e, axis=-1, keepdims=True)
        return jnp.sum(p[:, :, :, :, None] * v[:, :, None, :, :], axis=-2)

    return _apply(f, (query, key, value, start_pos), name="cached_attention")


def rope_positions(cos_table, sin_table, start_pos, length):
    """Gather per-row RoPE rows for positions ``start_pos[b] + [0..length)``
    from (S, D/2) tables; returns a ``(cos, sin)`` pair shaped
    (B, 1, length, D/2) — broadcastable over the head axis."""

    def f(ct, st, sp):
        jnp = _jnp()
        pos = sp.astype(jnp.int32)[:, None] \
            + jnp.arange(length, dtype=jnp.int32)[None, :]       # (B, T)
        return jnp.take(ct, pos, axis=0)[:, None], \
            jnp.take(st, pos, axis=0)[:, None]

    return _apply(f, (cos_table, sin_table, start_pos),
                  name="rope_positions")


def stable_dense(data, weight, bias=None):
    """Shape-stable fully-connected: ``data`` (..., U) x ``weight`` (O, U)
    -> (..., O), reducing over the last axis with the same mul+reduce
    formulation as ``cached_attention``.

    XLA CPU's gemm/gemv dispatch accumulates in an M-dependent order once
    the intra-op thread pool partitions the work (measured 1e-5 drift
    between the T=1 and T=64 rows of the SAME projection under the test
    mesh), so a ``dot``-based projection breaks the decode-vs-prefill
    bitwise contract. Here every output element is one sequential chain
    over U regardless of the leading shape. Serving-path only: training
    keeps ``fully_connected``'s gemm (MXU/BLAS) throughput.
    """

    def f(x, w, *b):
        jnp = _jnp()
        out = jnp.sum(x[..., None, :] * w, axis=-1)
        return out + b[0] if b else out

    args = (data, weight) if bias is None else (data, weight, bias)
    return _apply(f, args, name="stable_dense")


def fusion_fence(data):
    """Identity that pins ``data`` as an XLA fusion boundary
    (``optimization_barrier``). The serving decode path threads one
    between decoder layers: without it XLA fuses reductions across layer
    boundaries differently for the T=1 and T=bucket executables (measured
    ~4 ulp logits drift on the 12-layer config), which would break the
    decode-vs-prefill bitwise contract the shape-stable ops above
    establish per layer."""

    def f(x):
        import jax

        return jax.lax.optimization_barrier(x)

    return _apply(f, (data,), name="fusion_fence")


def gather_positions(data, indices):
    """Per-row gather along axis 1: ``data`` (B, T, ...) at ``indices``
    (B,) -> (B, ...). Serving uses it to pick each request's last-real-
    position logits out of a padded prefill block."""

    def f(x, i):
        jnp = _jnp()
        idx = i.astype(jnp.int32).reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.take_along_axis(x, idx, axis=1)[:, 0]

    return _apply(f, (data, indices), name="gather_positions")


def sample_step(logits, temperature, top_k, seeds, positions, key_bits):
    """In-trace next-token sampling for the multi-step decode super-step
    (``serve.generate._MultiStepForward``).

    ``logits`` (B, V) f32; per-row ``temperature`` (B,) f32 (<= 0 means
    greedy argmax — matching ``serve.generate.sample_tokens``), ``top_k``
    (B,) int32 (0 or >= V means no truncation), ``seeds`` (B,) int32 (one
    stream per serving slot) and ``positions`` (B,) int32 (the absolute
    decode position being sampled). ``key_bits`` is a (2,) uint32 raw
    threefry2x32 key — an ordinary traced input, NOT a baked constant, so
    one compiled executable serves every reseed.

    Keying is counter-based, not stateful: row ``b``'s key is
    ``fold_in(fold_in(key_bits, seeds[b]), positions[b])`` — a pure
    function of (base, slot stream, position). That is what makes the
    token stream invariant to super-step boundaries: running N=8
    iterations per compiled loop or degrading the same executable to
    N=1 draws the identical key for every position, so sampled output
    is token-identical across ``steps_limit`` choices (a stateful
    ``mx.random`` draw would advance once per TRACE, not per iteration,
    and every loop iteration would reuse one key).

    Returns (B,) int32 sampled token ids.
    """

    def f(lg, temp, tk, sd, pos, kb):
        import jax

        jnp = _jnp()
        v = lg.shape[-1]
        greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        base = jax.random.wrap_key_data(kb.astype(jnp.uint32),
                                        impl="threefry2x32")

        def row(l, t, k, s, p):
            key = jax.random.fold_in(
                jax.random.fold_in(base, s.astype(jnp.int32)),
                p.astype(jnp.int32))
            scaled = l / jnp.maximum(t, 1e-6)
            # per-row dynamic top-k: threshold at the k-th largest logit
            # (descending sort; same tie semantics as sample_tokens'
            # static jax.lax.top_k truncation — values >= kth survive)
            srt = jnp.sort(scaled)[::-1]
            kth = srt[jnp.clip(k, 1, v) - 1]
            keep = jnp.where((k > 0) & (k < v), scaled >= kth, True)
            return jax.random.categorical(
                key, jnp.where(keep, scaled, -jnp.inf))

        def drawn(_):
            sampled = jax.vmap(row)(
                lg, temp.astype(jnp.float32), tk.astype(jnp.int32),
                sd, pos).astype(jnp.int32)
            return jnp.where(temp > 0.0, sampled, greedy)

        # lax.cond, not where: an all-greedy batch (the bench rungs, every
        # temperature-0 request mix) must not pay the per-row vocab sort +
        # categorical draw on its decode critical path — the sampled
        # branch only executes when some lane actually wants it
        return jax.lax.cond(jnp.any(temp > 0.0), drawn,
                            lambda _: greedy, 0)

    return _apply(f, (logits, temperature, top_k, seeds, positions,
                      key_bits), name="sample_step")


# ---------------------------------------------------------------------------
# Paged KV-cache ops (mxnet_tpu.serve.kv_blocks / serve.scheduler)
#
# The continuous-batching decode loop stores every request's KV rows in
# one device-resident *page pool* per layer — (P, KV, page, D) for the
# rings, (P, KV, page) for the int8 scale pools — instead of per-bucket
# contiguous rings. A per-slot page table (B, N) of pool page ids maps
# each slot's logical ring onto its owned pages; page id 0 is the
# reserved NULL page (dead/idle slots point every entry at it).
#
# Both ops below are pure data movement (jnp.take / scatter-set — never
# an arithmetic merge): gather(pool) -> kv_cache_write/cached_attention
# -> scatter reads and writes exactly the bytes the contiguous path
# would. The strict baseline rung runs them as standalone eager ops
# around the unchanged ring executable, which keeps its bitwise decode
# contract; compiled INTO the step (fast rungs), XLA partitions the
# attention loops differently for a gather-fed ring than for an entry
# parameter, which drifts ulps — tolerance parity only.
# ---------------------------------------------------------------------------


def paged_kv_gather(pool, page_table):
    """Materialize per-slot contiguous KV rings from a paged pool.

    ``pool`` is (P, KV, page, D) — or (P, KV, page) for a scale pool —
    and ``page_table`` (B, N) int32 maps slot ``b``'s logical page ``i``
    to a pool page id (0 = the reserved null page, which the serving
    step keeps zeroed). Returns the (B, KV, N*page, D) ring — an exact
    copy (``jnp.take``), bit-preserving by construction. Positions the
    slot does not own read null-page zeros; the attention position mask
    (``s <= start_pos + t``) guarantees they are never attended before
    being overwritten.
    """

    def f(p, t):
        jnp = _jnp()
        g = jnp.take(p, t.astype(jnp.int32), axis=0)  # (B, N, KV, pg[, D])
        if p.ndim == 4:
            g = g.transpose(0, 2, 1, 3, 4)
            b, kv, n, pg, d = g.shape
            return g.reshape(b, kv, n * pg, d)
        g = g.transpose(0, 2, 1, 3)
        b, kv, n, pg = g.shape
        return g.reshape(b, kv, n * pg)

    return _apply(f, (pool, page_table), name="paged_kv_gather")


def paged_kv_scatter(pool, page_table, ring, start_pos, length):
    """Write the ``length`` freshly-written ring rows at positions
    ``start_pos[b] + [0..length)`` of ``ring`` (B, KV, S, D) back into the
    paged ``pool`` through ``page_table`` (B, N). 3-D scale rings
    (B, KV, S) scatter into (P, KV, page) pools the same way.

    Exact copy in both directions: rows are extracted with
    ``take_along_axis`` and written with a scatter-``set`` (copied, not
    merged). Slots whose table rows are all-null (dead/idle lanes of a
    fixed-width decode step) land their writes on page 0; page 0 is
    re-zeroed at the end of the op, so the null page reads as zeros on
    every gather — dead lanes can never feed garbage back to themselves
    across steps.
    """

    def f(p, t, r, sp):
        jnp = _jnp()
        page = p.shape[2]
        n_pages = t.shape[1]
        s_len = r.shape[2]
        pos = sp.astype(jnp.int32)[:, None] \
            + jnp.arange(length, dtype=jnp.int32)[None, :]          # (B, L)
        pos = jnp.clip(pos, 0, s_len - 1)
        pid = jnp.take_along_axis(
            t.astype(jnp.int32),
            jnp.clip(pos // page, 0, n_pages - 1), axis=1)          # (B, L)
        off = pos % page                                            # (B, L)
        if r.ndim == 4:
            rows = jnp.take_along_axis(r, pos[:, None, :, None], axis=2)
            vals = rows.transpose(0, 2, 1, 3)                # (B, L, KV, D)
        else:
            rows = jnp.take_along_axis(r, pos[:, None, :], axis=2)
            vals = rows.transpose(0, 2, 1)                   # (B, L, KV)
        out = p.at[pid, :, off].set(vals)
        # keep the null-page invariant: page 0 always reads as zeros
        return out.at[0].set(jnp.zeros_like(out[0]))

    return _apply(f, (pool, page_table, ring, start_pos),
                  name="paged_kv_scatter")


# ---------------------------------------------------------------------------
# misc framework extras
# ---------------------------------------------------------------------------


def reshape(data, newshape, reverse=False, order="C"):  # pylint: disable=unused-argument
    return data.reshape(newshape)


def shape_array(data):
    from ..ndarray.ndarray import NDArray

    return NDArray(_onp.asarray(data.shape, _onp.int64))


def cast(data, dtype):
    return data.astype(dtype)


def slice(data, begin, end, step=None):  # pylint: disable=redefined-builtin
    import builtins

    nd = len(begin)
    step = step or (1,) * nd
    idx = tuple(builtins.slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


def slice_axis(data, axis, begin, end):
    import builtins

    idx = [builtins.slice(None)] * data.ndim
    idx[axis] = builtins.slice(begin, end)
    return data[tuple(idx)]


def slice_like(data, shape_like, axes=None):
    import builtins

    target = shape_like.shape
    idx = [builtins.slice(None)] * data.ndim
    for ax in (axes if axes is not None else range(data.ndim)):
        idx[ax] = builtins.slice(0, target[ax])
    return data[tuple(idx)]


def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):  # pylint: disable=unused-argument
    return lhs.broadcast_to(rhs.shape)


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    jnp = _jnp()

    def f(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    return _apply(f, (lhs, rhs), name="batch_dot")


def concat(*data, dim=1):
    """Concatenate along ``dim`` (reference op ``Concat``/``concat``,
    ``src/operator/nn/concat.cc``). Delegates to the numpy namespace so
    there is a single concat implementation."""
    from .. import numpy as _mxnp

    return _mxnp.concatenate(list(data), axis=dim)


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):  # pylint: disable=unused-argument
    jnp = _jnp()
    from ..ndarray.ndarray import NDArray

    n = data.size if axis is None else data.shape[axis]
    return NDArray(jnp.arange(n) * step + start)


def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape ``lhs`` to ``rhs``'s shape (reference
    ``src/operator/tensor/elemwise_unary_op_basic.cc`` reshape_like);
    the begin/end variants splice a sub-range of rhs dims."""
    shape = list(rhs.shape)
    if any(v is not None for v in (lhs_begin, lhs_end, rhs_begin, rhs_end)):
        lb = 0 if lhs_begin is None else lhs_begin
        le = len(lhs.shape) if lhs_end is None else lhs_end
        rb = 0 if rhs_begin is None else rhs_begin
        re_ = len(shape) if rhs_end is None else rhs_end
        shape = list(lhs.shape[:lb]) + shape[rb:re_] + list(lhs.shape[le:])
    t = tuple(int(s) for s in shape)
    return _apply(lambda x: x.reshape(t), (lhs,), name="reshape_like")


def stop_gradient(data):
    """Identity whose gradient is blocked (reference ``BlockGrad``)."""
    return _apply(lambda x: x, (data,), name="stop_gradient", record=False)


def cast_storage(data, stype="default"):
    """Convert between dense and sparse storage (reference
    ``src/operator/tensor/cast_storage.cc``)."""
    from ..ndarray.ndarray import NDArray
    from ..ndarray.sparse import BaseSparseNDArray, dense_to_sparse

    if isinstance(data, BaseSparseNDArray):
        return data.tostype(stype)
    nd = data if isinstance(data, NDArray) else NDArray(data)
    if stype == "default":
        return nd
    return dense_to_sparse(nd, stype)


def depth_to_space(data, block_size):
    """(B, C·b², H, W) → (B, C, H·b, W·b) (reference
    ``src/operator/tensor/matrix_op.cc`` DepthToSpace: DCR order)."""
    b = int(block_size)

    def f(x):
        n, c, h, w = x.shape
        x = x.reshape(n, b, b, c // (b * b), h, w)
        x = x.transpose(0, 3, 4, 1, 5, 2)
        return x.reshape(n, c // (b * b), h * b, w * b)

    return _apply(f, (data,), name="depth_to_space")


def space_to_depth(data, block_size):
    """(B, C, H·b, W·b) → (B, C·b², H, W) — exact inverse of
    ``depth_to_space``."""
    b = int(block_size)

    def f(x):
        n, c, hb, wb = x.shape
        h, w = hb // b, wb // b
        x = x.reshape(n, c, h, b, w, b)
        x = x.transpose(0, 3, 5, 1, 2, 4)
        return x.reshape(n, c * b * b, h, w)

    return _apply(f, (data,), name="space_to_depth")


def im2col(data, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Sliding-window patch extraction (reference
    ``src/operator/nn/im2col.h`` semantics): (B, C, H, W) →
    (B, C·kh·kw, OH·OW) with (C, kh, kw) channel-major patch order."""
    kh, kw = _tup(kernel, 2)
    sh, sw = _tup(stride, 2)
    dh, dw = _tup(dilate, 2)
    ph, pw = _tup(pad, 2)

    def f(x):
        import jax

        n, c = x.shape[0], x.shape[1]
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
            rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # (B, C*kh*kw, OH, OW) with channel-major order already
        return patches.reshape(n, c * kh * kw, -1)

    return _apply(f, (data,), name="im2col")


def col2im(data, output_size, kernel, stride=(1, 1), dilate=(1, 1),
           pad=(0, 0)):
    """Inverse of :func:`im2col`: overlapping patches scatter-ADD back
    into the (B, C, H, W) image (reference ``col2im`` in
    ``src/operator/nn/im2col.h``). Implemented as the exact vjp of the
    patch extraction — transposes are the compiler's problem."""
    oh, ow = _tup(output_size, 2)
    kh, kw = _tup(kernel, 2)
    sh, sw = _tup(stride, 2)
    dh, dw = _tup(dilate, 2)
    ph, pw = _tup(pad, 2)

    def f(cols):
        import jax

        n = cols.shape[0]
        c = cols.shape[1] // (kh * kw)

        def fwd(img):
            p = jax.lax.conv_general_dilated_patches(
                img, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
                rhs_dilation=(dh, dw),
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            return p.reshape(n, c * kh * kw, -1)

        zero = _jnp().zeros((n, c, oh, ow), cols.dtype)
        _, vjp = jax.vjp(fwd, zero)
        (img,) = vjp(cols)
        return img

    return _apply(f, (data,), name="col2im")


def adaptive_avg_pooling2d(data, output_size=1):
    """Adaptive average pooling (reference
    ``src/operator/contrib/adaptive_avg_pooling.cc``): output bin (i, j)
    averages input span [floor(i·H/oh), ceil((i+1)·H/oh)) — the
    overlapping-span geometry, computed as two masked mean reductions
    (static shapes; the spans are compile-time constants)."""
    jnp = _jnp()
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def f(x):
        import math as _m

        B, C, H, W = x.shape

        def masks(n, o):
            m = _onp.zeros((o, n), "float32")
            for b in range(o):
                lo = _m.floor(b * n / o)
                hi = _m.ceil((b + 1) * n / o)
                m[b, lo:hi] = 1.0 / (hi - lo)
            return jnp.asarray(m)

        mh = masks(H, oh)  # (oh, H), rows sum to 1
        mw = masks(W, ow)  # (ow, W)
        t = jnp.einsum("bchw,ow->bcho", x, mw)
        return jnp.einsum("bcho,ph->bcpo", t, mh)

    return _apply(f, (data,), name="adaptive_avg_pooling2d")


def hard_sigmoid(data, alpha=0.2, beta=0.5):
    """Piecewise-linear sigmoid (reference ``HardSigmoid`` in
    ``src/operator/nn/activation``-adjacent LeakyReLU family)."""
    jnp = _jnp()
    return _apply(lambda x: jnp.clip(alpha * x + beta, 0.0, 1.0), (data,),
                  name="hard_sigmoid")


def gamma(data):
    """Elementwise gamma function Γ(x) (reference ``nd.gamma``,
    ``src/operator/tensor/elemwise_unary_op``)."""

    def f(x):
        import jax.scipy.special as jsp

        jnp = _jnp()
        # Γ via lgamma: |Γ(x)| = exp(lgamma(x)); the sign alternates on
        # the negative axis: Γ(x) < 0 iff floor(x) is odd for x < 0
        # (poles at non-positive integers are ±inf either way)
        mag = jnp.exp(jsp.gammaln(x))
        if hasattr(jsp, "gammasgn"):
            sign = jsp.gammasgn(x)
        else:
            sign = jnp.where(
                (x < 0) & (jnp.floor(x) % 2 != 0), -1.0, 1.0
            ).astype(x.dtype)
        return sign * mag

    return _apply(f, (data,), name="gamma")


def gammaln(data):
    def f(x):
        import jax.scipy.special as jsp

        return jsp.gammaln(x)

    return _apply(f, (data,), name="gammaln")


def erfinv(data):
    import jax

    return _apply(jax.lax.erf_inv, (data,), name="erfinv")


def index_copy(old_tensor, index_vector, new_tensor):
    """Copy rows of ``new_tensor`` into ``old_tensor`` at ``index_vector``
    (reference ``src/operator/contrib/index_copy.cc``)."""
    jnp = _jnp()

    def f(old, idx, new):
        return old.at[idx.astype(jnp.int32)].set(new)

    return _apply(f, (old_tensor, index_vector, new_tensor),
                  name="index_copy")


def index_array(data, axes=None):
    """Element-index grid of ``data``'s shape (reference
    ``src/operator/contrib/index_array.cc``): out[..., k] = index along
    the k-th listed axis."""
    jnp = _jnp()
    axes_t = tuple(axes) if axes is not None else None

    def f(x):
        sel = axes_t if axes_t is not None else tuple(range(x.ndim))
        grids = [jnp.broadcast_to(
            jnp.arange(x.shape[a]).reshape(
                [-1 if i == a else 1 for i in range(x.ndim)]), x.shape)
            for a in sel]
        return jnp.stack(grids, axis=-1).astype(jnp.int64)

    return _apply(f, (data,), name="index_array", record=False)


def boolean_mask(data, index, axis=0):
    """Select slices where ``index`` is nonzero (reference
    ``src/operator/contrib/boolean_mask.cc``). Output size is
    data-dependent, so this op is EAGER-ONLY (SURVEY §7 hard part 3) —
    inside jit use ``jnp.where``-style masking instead."""
    import jax
    import numpy as onp

    from ..base import MXNetError
    from ..ndarray.ndarray import NDArray

    d = data._data if isinstance(data, NDArray) else data
    m = index._data if isinstance(index, NDArray) else index
    if isinstance(d, jax.core.Tracer) or isinstance(m, jax.core.Tracer):
        raise MXNetError(
            "boolean_mask has a data-dependent output shape and cannot run "
            "under jit/hybridize; use arithmetic masking inside traces")
    keep = onp.nonzero(onp.asarray(m) != 0)[0]
    jnp = _jnp()
    return _apply(
        lambda x: jnp.take(x, jnp.asarray(keep), axis=axis), (data,),
        name="boolean_mask", cacheable=False)


# register the public ops in the global registry for list_ops parity
for _name in (
    "activation", "fully_connected", "convolution", "deconvolution", "pooling",
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "rms_norm",
    "dropout", "softmax", "log_softmax", "masked_softmax", "embedding",
    "one_hot", "pick", "topk", "sequence_mask", "sequence_last",
    "sequence_reverse", "ctc_loss", "attention", "leaky_relu", "relu",
    "sigmoid", "tanh", "batch_dot", "gather_nd", "scatter_nd", "concat",
    "hard_sigmoid", "gamma", "gammaln", "erfinv", "index_copy",
    "adaptive_avg_pooling2d", "reshape_like", "stop_gradient",
    "cast_storage", "depth_to_space", "space_to_depth", "im2col", "col2im",
    "index_array", "boolean_mask",
):
    _register(_name, globals()[_name], wrapper=True)
