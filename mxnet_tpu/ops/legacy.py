"""Legacy ``mx.nd`` / ``mx.sym`` op surface — shared resolver.

The reference synthesizes the full legacy op namespace onto ``mx.nd`` and
``mx.sym`` at import by enumerating the C op registry
(``python/mxnet/ndarray/register.py:115-265``); Gluon-v1-era scripts are
written against these names, including the CamelCase layer ops
(``nd.FullyConnected``, ``nd.Convolution``, …) registered in
``src/operator/nn/*.cc`` and the broadcast/elemwise families of
``src/operator/tensor/``.

This module is the single source of truth for that surface in the TPU
build. Resolution order for a legacy name (:func:`resolve`):

1. ``ALIASES`` — legacy spelling → canonical name (then continue the chain)
2. ``FUNCS`` — legacy ops whose semantics differ from any ``mx.np`` function
   (``flatten`` → 2-D, ``slice_axis``, broadcast_* family, fused optimizer
   update kernels, …), implemented here over the numpy namespace so
   autograd recording and the eager jit cache compose
3. the op registry (``ops.registry``) — NN/contrib ops
4. ``mx.np`` then ``mx.npx`` attributes
5. ``NOT_SUPPORTED`` — deliberate refusals that resolve to a callable
   raising :class:`MXNetError` with guidance (the Horovod-stub pattern),
   so every reference-registry name resolves to code or a documented "no"

Both ``mxnet_tpu.ndarray.__getattr__`` and ``symbol._resolve_op`` go
through :func:`resolve`, so the two legacy namespaces cannot drift apart
again (VERDICT r3 Weak #1).
"""
from __future__ import annotations

from ..base import MXNetError

# ---------------------------------------------------------------------------
# Alias table: legacy (mostly CamelCase) name -> canonical resolvable name.
# Reference registrations: src/operator/nn/*.cc, src/operator/tensor/*.cc.
# ---------------------------------------------------------------------------
ALIASES = {
    # NN layer ops (src/operator/nn/)
    "FullyConnected": "fully_connected",
    "Convolution": "convolution",
    "Deconvolution": "deconvolution",
    "Activation": "activation",
    "BatchNorm": "batch_norm",
    "LayerNorm": "layer_norm",
    "GroupNorm": "group_norm",
    "InstanceNorm": "instance_norm",
    "Pooling": "pooling",
    "Dropout": "dropout",
    "Embedding": "embedding",
    "Concat": "concat",
    "Softmax": "softmax",
    "SoftmaxActivation": "softmax",
    "LeakyReLU": "leaky_relu",
    "CTCLoss": "ctc_loss",
    # tensor manipulation (src/operator/tensor/)
    "Flatten": "flatten",
    "Reshape": "reshape",
    "Cast": "cast",
    "SwapAxis": "swapaxes",
    "SliceChannel": "slice_channel",
    "Pad": "pad_legacy",
    "UpSampling": "upsampling",
    "BlockGrad": "stop_gradient",
    "MakeLoss": "make_loss",
    "LRN": "lrn",
    # sequence ops (src/operator/sequence_*.cc)
    "SequenceMask": "sequence_mask",
    "SequenceLast": "sequence_last",
    "SequenceReverse": "sequence_reverse",
    # spatial / contrib (src/operator/{bilinear_sampler,grid_generator}.cc)
    "BilinearSampler": "bilinear_sampler",
    "GridGenerator": "grid_generator",
    "SpatialTransformer": "spatial_transformer",
    "ROIPooling": "roi_pooling",
    "Correlation": "correlation",
    "DeformableConvolution": "deformable_convolution",
    "L2Normalization": "l2_normalization",
    # numpy-spelling drift
    "stop_gradient": "stop_gradient",
    "identity": "copy",
    "modulo": "mod",
    "lesser": "less",
    "lesser_equal": "less_equal",
    "split": "slice_channel",   # legacy nd.split == SliceChannel semantics
    "flip": "reverse",          # legacy flip requires axis, like reverse
    "crop": "slice_legacy",     # legacy nd.crop == nd.slice
    "slice": "slice_legacy",
    "pad": "pad_legacy",
    "random_uniform": "random_uniform",
    "random_normal": "random_normal",
    "uniform": "random_uniform",
    "normal": "random_normal",
    "ElementWiseSum": "add_n",
    "elemwise_sub": "elemwise_sub",
    "elemwise_div": "elemwise_div",
    # aliases the C registry declares via .add_alias
    "choose_element_0index": "pick",
    "max_axis": "max",
    "min_axis": "min",
    "sum_axis": "sum",
    "negative_binomial": "random_negative_binomial",
    "generalized_negative_binomial":
        "random_generalized_negative_binomial",
    "shuffle": "shuffle_legacy",
}

# broadcast_* binary family -> mx.np binary op (reference:
# src/operator/tensor/elemwise_binary_broadcast_op_{basic,logic,extended}.cc;
# jax.numpy broadcasts by default, so these are direct delegations)
_BROADCAST_BINARY = {
    "broadcast_add": "add",
    "broadcast_plus": "add",
    "broadcast_sub": "subtract",
    "broadcast_minus": "subtract",
    "broadcast_mul": "multiply",
    "broadcast_div": "divide",
    "broadcast_mod": "mod",
    "broadcast_power": "power",
    "broadcast_maximum": "maximum",
    "broadcast_minimum": "minimum",
    "broadcast_hypot": "hypot",
    "broadcast_equal": "equal",
    "broadcast_not_equal": "not_equal",
    "broadcast_greater": "greater",
    "broadcast_greater_equal": "greater_equal",
    "broadcast_lesser": "less",
    "broadcast_lesser_equal": "less_equal",
    "broadcast_logical_and": "logical_and",
    "broadcast_logical_or": "logical_or",
    "broadcast_logical_xor": "logical_xor",
}


def _np():
    from .. import numpy as mnp

    return mnp


def _npx():
    from .. import numpy_extension as npx

    return npx


def _registry():
    from . import registry

    return registry


def _write_out(res, out):
    """Honor a legacy ``out=`` destination (mutation-rebind, engine var
    discipline lives in NDArray._set_data_internal)."""
    if out is None:
        return res
    out._set_data_internal(res._data)
    out._tape = getattr(res, "_tape", None)
    return out


# ---------------------------------------------------------------------------
# Legacy ops with semantics that differ from mx.np
# ---------------------------------------------------------------------------


def flatten(data, **kwargs):
    """Legacy 2-D flatten: (N, x, y, z) -> (N, x*y*z)
    (reference ``Flatten``, src/operator/tensor/matrix_op.cc)."""
    import numpy as onp

    return _np().reshape(data, (data.shape[0], int(onp.prod(data.shape[1:], dtype=onp.int64))))


def infer_reshape_shape(spec, src_shape, reverse=False):
    """The reference's reshape special values (``matrix_op-inl.h``
    ``InferReshapeShape``): 0 = copy input dim, -1 = infer one dim,
    -2 = copy all remaining input dims, -3 = merge two consecutive input
    dims, -4 d1 d2 = split one input dim (either may be -1).
    ``reverse=True`` runs the algorithm right-to-left."""
    spec = list(spec)
    src = list(src_shape)
    if reverse:
        spec.reverse()
        src.reverse()
    out, src_idx, inf_idx, i = [], 0, -1, 0
    while i < len(spec):
        v = spec[i]
        if v == 0:
            if src_idx >= len(src):
                raise ValueError(f"reshape spec {tuple(spec)} runs past "
                                 f"input shape {tuple(src_shape)}")
            out.append(src[src_idx]); src_idx += 1
        elif v == -1:
            if inf_idx >= 0:
                raise ValueError("One and only one dim can be inferred")
            inf_idx = len(out)
            out.append(1); src_idx += 1
        elif v == -2:
            out.extend(src[src_idx:]); src_idx = len(src)
        elif v == -3:
            if src_idx + 1 >= len(src):
                raise ValueError(f"-3 needs two input dims at position "
                                 f"{src_idx} of {tuple(src_shape)}")
            out.append(src[src_idx] * src[src_idx + 1]); src_idx += 2
        elif v == -4:
            if i + 2 >= len(spec) or src_idx >= len(src):
                raise ValueError("-4 must be followed by two split dims")
            d0 = src[src_idx]; src_idx += 1
            d1, d2 = spec[i + 1], spec[i + 2]; i += 2
            if d1 == -1 and d2 == -1:
                raise ValueError("Split dims cannot both be -1.")
            if d1 == -1:
                d1 = d0 // d2
            if d2 == -1:
                d2 = d0 // d1
            if d1 * d2 != d0:
                raise ValueError(f"Split dims {d1}, {d2} do not divide "
                                 f"original dim {d0}")
            out.extend([d1, d2])
        else:
            out.append(v); src_idx += 1
        i += 1
    if inf_idx >= 0:
        import numpy as onp
        known = int(onp.prod(out, dtype=onp.int64))
        total = int(onp.prod(src, dtype=onp.int64))
        out[inf_idx] = total // known
    if reverse:
        out.reverse()
    return tuple(out)


def reshape(data, shape=None, reverse=False, out=None, **kwargs):
    """Legacy ``nd.reshape`` incl. special values 0/-1/-2/-3/-4 and
    ``reverse`` (reference ``Reshape``, src/operator/tensor/matrix_op.cc)."""
    new_shape = infer_reshape_shape(shape, data.shape, reverse)
    return _write_out(_np().reshape(data, new_shape), out)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32",
           infer_range=None, out=None, **kwargs):
    """Legacy ``nd.arange``: float32 default dtype and element-wise
    ``repeat`` (reference ndarray.py ``arange`` docstring:
    ``arange(2, 6, step=1.5, repeat=2) -> [2, 2, 3.5, 3.5, 5, 5]``)."""
    if stop is None:
        start, stop = 0, start
    res = _np().arange(start, stop, step, dtype=dtype, ctx=ctx)
    if repeat != 1:
        res = res.repeat(repeat)
    return _write_out(res, out)


def cast(data, dtype, **kwargs):
    return data.astype(dtype)


def slice_legacy(data, begin, end, step=None, out=None, **kwargs):
    """Legacy ``nd.slice`` (src/operator/tensor/matrix_op.cc ``slice``):
    per-axis begin/end tuples, None = full extent."""
    idx = []
    step = step or [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins_slice(b, e, s))
    return _write_out(data[tuple(idx)], out)


builtins_slice = slice  # keep the builtin reachable under the op name


def slice_axis(data, axis=0, begin=0, end=None, **kwargs):
    idx = [builtins_slice(None)] * data.ndim
    idx[axis] = builtins_slice(begin, end)
    return data[tuple(idx)]


def slice_like(data, shape_like, axes=(), **kwargs):
    axes = list(axes) if axes else list(range(min(data.ndim, shape_like.ndim)))
    idx = [builtins_slice(None)] * data.ndim
    for ax in axes:
        idx[ax] = builtins_slice(0, shape_like.shape[ax])
    return data[tuple(idx)]


def slice_channel(data, num_outputs=1, axis=1, squeeze_axis=False, **kwargs):
    """Legacy ``SliceChannel`` / ``nd.split``."""
    outs = _np().split(data, num_outputs, axis=axis)
    if squeeze_axis:
        outs = [_np().squeeze(o, axis=axis) for o in outs]
    return list(outs)


def broadcast_axis(data, axis=0, size=1, **kwargs):
    axes = axis if isinstance(axis, (tuple, list)) else (axis,)
    sizes = size if isinstance(size, (tuple, list)) else (size,)
    shape = list(data.shape)
    for ax, s in zip(axes, sizes):
        shape[ax] = s
    return _np().broadcast_to(data, tuple(shape))


broadcast_axes = broadcast_axis


def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None, **kwargs):
    if lhs_axes is None:
        return _np().broadcast_to(lhs, rhs.shape)
    shape = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        shape[la] = rhs.shape[ra]
    return _np().broadcast_to(lhs, tuple(shape))


def reverse(data, axis=0, **kwargs):
    return _np().flip(data, axis=axis)


def tile_legacy(data, reps, **kwargs):
    return _np().tile(data, reps)


def add_n(*args, out=None, **kwargs):
    res = args[0]
    for a in args[1:]:
        res = res + a
    return _write_out(res, out)


def elemwise_add(lhs, rhs, **kwargs):
    return lhs + rhs


def elemwise_mul(lhs, rhs, **kwargs):
    return lhs * rhs


def elemwise_sub(lhs, rhs, **kwargs):
    return lhs - rhs


def elemwise_div(lhs, rhs, **kwargs):
    return lhs / rhs


def make_loss(data, **kwargs):
    """Legacy ``MakeLoss``: in the reference this marks an output as a loss
    head for the (removed) Module API; under autograd it is identity."""
    return data


def shape_array(data, **kwargs):
    import numpy as onp

    return _np().array(onp.array(data.shape, dtype=onp.int64))


def size_array(data, **kwargs):
    import numpy as onp

    return _np().array(onp.array([data.size], dtype=onp.int64))


def argmax_channel(data, **kwargs):
    """Argmax over axis 1, returned in the input dtype
    (reference src/operator/tensor/broadcast_reduce_op_index.cc)."""
    return _np().argmax(data, axis=1).astype(data.dtype)


def batch_take(a, indices, **kwargs):
    return _registry().get("pick")(a, indices, axis=1)


def smooth_l1(data, scalar=1.0, **kwargs):
    """Reference src/operator/loss_binary_op (smooth_l1):
    0.5*(s*x)^2 if |x| < 1/s^2 else |x| - 0.5/s^2."""
    mnp = _np()
    s2 = scalar * scalar
    absx = mnp.abs(data)
    return mnp.where(absx < 1.0 / s2,
                     0.5 * s2 * data * data,
                     absx - 0.5 / s2)


def softmax_cross_entropy(data, label, **kwargs):
    """Reference src/operator/loss_binary_op-inl.h: total (summed) CE over
    the batch, returned as a 1-element array."""
    mnp = _np()
    lsm = _registry().get("log_softmax")(data, axis=-1)
    picked = _registry().get("pick")(lsm, label, axis=-1)
    return mnp.reshape(-mnp.sum(picked), (1,))


def softmin(data, axis=-1, **kwargs):
    return _registry().get("softmax")(-data, axis=axis)


def softsign(data, **kwargs):
    return data / (1 + _np().abs(data))


def norm(data, ord=2, axis=None, keepdims=False, out=None, **kwargs):  # pylint: disable=redefined-builtin
    mnp = _np()
    if ord == 1:
        res = mnp.sum(mnp.abs(data), axis=axis, keepdims=keepdims)
    else:
        res = mnp.sqrt(mnp.sum(data * data, axis=axis, keepdims=keepdims))
    return _write_out(res, out)


def moments(data, axes=None, keepdims=False, **kwargs):
    """Reference src/operator/nn/moments.cc: (mean, var) over ``axes``."""
    mnp = _np()
    mean = mnp.mean(data, axis=axes, keepdims=True)
    var = mnp.mean((data - mean) * (data - mean), axis=axes,
                   keepdims=keepdims)
    if not keepdims:
        mean = mnp.squeeze(mean, axis=axes)
    return [mean, var]


def khatri_rao(*args, **kwargs):
    """Column-wise Kronecker product (reference
    src/operator/contrib/krprod.cc): (n_i, k) inputs -> (prod n_i, k)."""
    mnp = _np()
    res = args[0]
    for m in args[1:]:
        res = mnp.reshape(
            mnp.expand_dims(res, 1) * mnp.expand_dims(m, 0),
            (res.shape[0] * m.shape[0], m.shape[1]))
    return res


def all_finite(data, init_output=True, **kwargs):
    mnp = _np()
    import numpy as onp

    return mnp.reshape(mnp.all(mnp.isfinite(data)).astype(onp.float32), (1,))


def multi_all_finite(*arrays, num_arrays=None, init_output=True, **kwargs):
    mnp = _np()
    res = all_finite(arrays[0])
    for a in arrays[1:]:
        res = res * all_finite(a)
    return mnp.reshape(res, (1,))


def amp_cast(data, dtype, **kwargs):
    return data.astype(dtype)


def amp_multicast(*data, num_outputs=None, cast_narrow=False, **kwargs):
    import numpy as onp

    dtypes = [onp.dtype(d.dtype) for d in data]
    target = min(dtypes, key=lambda t: t.itemsize) if cast_narrow else \
        max(dtypes, key=lambda t: t.itemsize)
    return [d.astype(target) for d in data]


def upsampling(data, scale=1, sample_type="nearest", num_args=1, **kwargs):
    """Legacy ``UpSampling`` nearest mode (src/operator/nn/upsampling.cc);
    bilinear mode used a learned deconv filter — use
    ``npx.bilinear_resize2d`` / ``gluon.nn.Conv2DTranspose`` instead."""
    if sample_type != "nearest":
        raise MXNetError(
            "UpSampling(sample_type='bilinear') is not supported in the TPU "
            "build: use npx.bilinear_resize2d for resizing or "
            "gluon.nn.Conv2DTranspose for a learned upsampler")
    mnp = _np()
    out = mnp.repeat(data, scale, axis=2)
    return mnp.repeat(out, scale, axis=3)


def pad_legacy(data, mode="constant", pad_width=None, constant_value=0,
               **kwargs):
    """Legacy ``nd.Pad`` (src/operator/pad.cc): flat 2*ndim pad_width
    tuple, modes constant/edge/reflect."""
    pairs = tuple((pad_width[2 * i], pad_width[2 * i + 1])
                  for i in range(len(pad_width) // 2))
    mnp = _np()
    if mode == "constant":
        return mnp.pad(data, pairs, mode="constant",
                       constant_values=constant_value)
    return mnp.pad(data, pairs, mode=mode)


def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **kwargs):
    """Local response normalization across channels, NCHW
    (reference src/operator/nn/lrn.cc):
    out = data / (knorm + alpha/nsize * window_sum(data^2))^beta."""
    mnp = _np()
    sq = data * data
    half = nsize // 2
    # window sum over channel axis via padded cumulative sum: O(C) and
    # static-shape, XLA-fusable (no gather per offset)
    padded = _np().pad(sq, ((0, 0), (half + 1, half), (0, 0), (0, 0)))
    csum = mnp.cumsum(padded, axis=1)
    c = data.shape[1]
    win = csum[:, nsize:nsize + c] - csum[:, :c]
    return data / ((knorm + (alpha / nsize) * win) ** beta)


def erf(data, **kwargs):
    def _f(x):
        import jax

        return jax.scipy.special.erf(x)

    return _registry().apply(_f, (data,), name="erf")


def rsqrt(data, **kwargs):
    return 1.0 / _np().sqrt(data)


def rcbrt(data, **kwargs):
    return 1.0 / _np().cbrt(data)


def digamma(data, **kwargs):
    def _f(x):
        import jax

        return jax.scipy.special.digamma(x)

    return _registry().apply(_f, (data,), name="digamma")


def relu_legacy(data, **kwargs):
    return _registry().get("relu")(data)


# ---------------------------------------------------------------------------
# Random samplers (legacy spellings over mx.np.random; reference
# src/operator/random/sample_op.cc registers _random_uniform with aliases
# random_uniform/uniform, etc.)
# ---------------------------------------------------------------------------


def random_uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None,
                   out=None, **kwargs):
    res = _np().random.uniform(low, high, size=shape, dtype=dtype, ctx=ctx)
    return _write_out(res, out)


def random_normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None,
                  out=None, **kwargs):
    res = _np().random.normal(loc, scale, size=shape, dtype=dtype, ctx=ctx)
    return _write_out(res, out)


def random_gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None,
                 out=None, **kwargs):
    res = _np().random.gamma(alpha, scale=beta, size=shape, ctx=ctx)
    return _write_out(res, out)


def random_exponential(lam=1.0, shape=None, dtype=None, ctx=None, out=None,
                       **kwargs):
    res = _np().random.exponential(scale=1.0 / lam, size=shape, ctx=ctx)
    return _write_out(res, out)


def random_poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None,
                   **kwargs):
    res = _np().random.poisson(lam=lam, size=shape, ctx=ctx)
    return _write_out(res, out)


def random_randint(low, high=None, shape=None, dtype=None, ctx=None,
                   out=None, **kwargs):
    res = _np().random.randint(low, high, size=shape, ctx=ctx)
    return _write_out(res, out)


# ---------------------------------------------------------------------------
# Fused optimizer update kernels (reference src/operator/optimizer_op.cc;
# the Python optimizer classes call these on the reference, and old custom
# training loops call them directly). All mutate ``out``/the state arrays
# the way the reference kernels write through ``req[0] = kWriteInplace``.
# ---------------------------------------------------------------------------


def _prep_grad(grad, rescale_grad, clip_gradient, wd, weight):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = _np().clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True, out=None, **kwargs):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    return _write_out(weight - lr * g, out if out is not None else weight)


def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
                   out=None, **kwargs):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g
    mom._set_data_internal(new_mom._data)
    return _write_out(weight + new_mom, out if out is not None else weight)


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, out=None, **kwargs):
    mnp = _np()
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * g * g
    mean._set_data_internal(new_mean._data)
    var._set_data_internal(new_var._data)
    res = weight - lr * new_mean / (mnp.sqrt(new_var) + epsilon)
    return _write_out(res, out if out is not None else weight)


def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None, **kwargs):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom + g
    mom._set_data_internal(new_mom._data)
    res = weight - lr * (g + momentum * new_mom)
    return _write_out(res, out if out is not None else weight)


def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, out=None, **kwargs):
    g = _prep_grad(grad, rescale_grad, clip_gradient, 0.0, weight)
    res = weight - lr * (_np().sign(g) + wd * weight)
    return _write_out(res, out if out is not None else weight)


def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, out=None,
                  **kwargs):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - (1 - momentum) * g
    mom._set_data_internal(new_mom._data)
    res = weight + lr * _np().sign(new_mom) - lr * wd_lh * weight
    return _write_out(res, out if out is not None else weight)


def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None, **kwargs):
    mnp = _np()
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = (1 - gamma1) * g * g + gamma1 * n
    n._set_data_internal(new_n._data)
    res = weight - lr * g / mnp.sqrt(new_n + epsilon)
    return _write_out(res, out if out is not None else weight)


def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, out=None, **kwargs):
    mnp = _np()
    g = _prep_grad(grad, rescale_grad, clip_gradient, 0.0, weight)
    new_n = n + g * g
    sigma = (mnp.sqrt(new_n) - mnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    z._set_data_internal(new_z._data)
    n._set_data_internal(new_n._data)
    res = mnp.where(
        mnp.abs(new_z) <= lamda1,
        mnp.zeros_like(weight),
        -(new_z - mnp.sign(new_z) * lamda1)
        / ((beta + mnp.sqrt(new_n)) / lr + wd))
    return _write_out(res, out if out is not None else weight)


# ---------------------------------------------------------------------------
# linalg_* family (reference src/operator/tensor/la_op.cc: batched LAPACK
# ops over (..., m, n) operands; jnp.linalg/lax lower them onto the MXU
# and the TPU's QR/cholesky expansions)
# ---------------------------------------------------------------------------


def _op_t(a, transpose):
    jnp = __import__("jax.numpy", fromlist=["x"])
    return jnp.swapaxes(a, -1, -2) if transpose else a


def linalg_gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, **kwargs):
    def f(aa, bb, cc):
        jnp = __import__("jax.numpy", fromlist=["x"])
        return alpha * jnp.matmul(_op_t(aa, transpose_a),
                                  _op_t(bb, transpose_b)) + beta * cc

    return _registry().apply(f, (a, b, c), name="linalg_gemm")


def linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0,
                 **kwargs):
    def f(aa, bb):
        jnp = __import__("jax.numpy", fromlist=["x"])
        return alpha * jnp.matmul(_op_t(aa, transpose_a),
                                  _op_t(bb, transpose_b))

    return _registry().apply(f, (a, b), name="linalg_gemm2")


def linalg_syrk(a, transpose=False, alpha=1.0, **kwargs):
    def f(aa):
        jnp = __import__("jax.numpy", fromlist=["x"])
        at = jnp.swapaxes(aa, -1, -2)
        return alpha * (jnp.matmul(at, aa) if transpose
                        else jnp.matmul(aa, at))

    return _registry().apply(f, (a,), name="linalg_syrk")


def linalg_potrf(a, **kwargs):
    def f(aa):
        jnp = __import__("jax.numpy", fromlist=["x"])
        return jnp.linalg.cholesky(aa)

    return _registry().apply(f, (a,), name="linalg_potrf")


def linalg_potri(l, **kwargs):  # noqa: E741
    """Inverse of A from its Cholesky factor L (A = L L^T) — the LAPACK
    *potri* contract the reference documents."""
    def f(ll):
        import jax
        jnp = __import__("jax.numpy", fromlist=["x"])
        eye = jnp.broadcast_to(jnp.eye(ll.shape[-1], dtype=ll.dtype),
                               ll.shape)
        y = jax.scipy.linalg.solve_triangular(ll, eye, lower=True)
        return jnp.matmul(jnp.swapaxes(y, -1, -2), y)

    return _registry().apply(f, (l,), name="linalg_potri")


def linalg_trmm(a, b, transpose=False, rightside=False, lower=True,
                alpha=1.0, **kwargs):
    def f(aa, bb):
        jnp = __import__("jax.numpy", fromlist=["x"])
        tri = jnp.tril(aa) if lower else jnp.triu(aa)
        tri = _op_t(tri, transpose)
        out = jnp.matmul(bb, tri) if rightside else jnp.matmul(tri, bb)
        return alpha * out

    return _registry().apply(f, (a, b), name="linalg_trmm")


def linalg_trsm(a, b, transpose=False, rightside=False, lower=True,
                alpha=1.0, **kwargs):
    """Solve op(A) X = alpha B (X op(A) = alpha B when rightside)."""
    def f(aa, bb):
        import jax
        jnp = __import__("jax.numpy", fromlist=["x"])
        tri = jnp.tril(aa) if lower else jnp.triu(aa)
        if rightside:
            # X op(A) = aB  <=>  op(A)^T X^T = a B^T ; op(A)^T is the
            # opposite-triangle system, solved by flipping trans
            xt = jax.scipy.linalg.solve_triangular(
                tri, jnp.swapaxes(alpha * bb, -1, -2), lower=lower,
                trans=0 if transpose else 1)
            return jnp.swapaxes(xt, -1, -2)
        return jax.scipy.linalg.solve_triangular(
            tri, alpha * bb, lower=lower, trans=1 if transpose else 0)

    return _registry().apply(f, (a, b), name="linalg_trsm")


def linalg_gelqf(a, **kwargs):
    """LQ factorization A = L @ Q for (x, y) with x <= y; returns
    [Q, L] (la_op.cc: 'Q, L = gelqf(A)'). Via QR of A^T."""
    def f(aa):
        jnp = __import__("jax.numpy", fromlist=["x"])
        q_r, r = jnp.linalg.qr(jnp.swapaxes(aa, -1, -2), mode="reduced")
        # fix signs so L has positive diagonal (LAPACK convention)
        d = jnp.sign(jnp.diagonal(r, axis1=-2, axis2=-1))
        d = jnp.where(d == 0, 1.0, d).astype(aa.dtype)
        q_r = q_r * d[..., None, :]
        r = r * d[..., :, None]
        return jnp.swapaxes(q_r, -1, -2), jnp.swapaxes(r, -1, -2)

    out = _registry().apply(f, (a,), name="linalg_gelqf")
    return list(out)


def linalg_det(a, **kwargs):
    def f(aa):
        jnp = __import__("jax.numpy", fromlist=["x"])
        return jnp.linalg.det(aa)

    return _registry().apply(f, (a,), name="linalg_det")


def linalg_slogdet(a, **kwargs):
    def f(aa):
        jnp = __import__("jax.numpy", fromlist=["x"])
        sign, logdet = jnp.linalg.slogdet(aa)
        return sign, logdet

    return list(_registry().apply(f, (a,), name="linalg_slogdet"))


def linalg_inverse(a, **kwargs):
    def f(aa):
        jnp = __import__("jax.numpy", fromlist=["x"])
        return jnp.linalg.inv(aa)

    return _registry().apply(f, (a,), name="linalg_inverse")


def linalg_sumlogdiag(a, **kwargs):
    def f(aa):
        jnp = __import__("jax.numpy", fromlist=["x"])
        return jnp.sum(jnp.log(jnp.diagonal(aa, axis1=-2, axis2=-1)),
                       axis=-1)

    return _registry().apply(f, (a,), name="linalg_sumlogdiag")


def linalg_extractdiag(a, offset=0, **kwargs):
    def f(aa):
        jnp = __import__("jax.numpy", fromlist=["x"])
        return jnp.diagonal(aa, offset=offset, axis1=-2, axis2=-1)

    return _registry().apply(f, (a,), name="linalg_extractdiag")


def linalg_makediag(v, offset=0, **kwargs):
    def f(vv):
        import jax
        jnp = __import__("jax.numpy", fromlist=["x"])
        mk = lambda x: jnp.diag(x, k=offset)  # noqa: E731
        for _ in range(vv.ndim - 1):
            mk = jax.vmap(mk)
        return mk(vv)

    return _registry().apply(f, (v,), name="linalg_makediag")


def _trian_indices(n, offset, lower):
    import numpy as onp

    if offset > 0:
        lower = False
    elif offset < 0:
        lower = True
    rows, cols = (onp.tril_indices(n, offset) if lower
                  else onp.triu_indices(n, offset))
    return rows, cols, lower


def linalg_extracttrian(a, offset=0, lower=True, **kwargs):
    """Packed triangle, row-major (la_op.cc extracttrian packing)."""
    n = a.shape[-1]
    rows, cols, _ = _trian_indices(n, offset, lower)

    def f(aa):
        return aa[..., rows, cols]

    return _registry().apply(f, (a,), name="linalg_extracttrian")


def linalg_maketrian(v, offset=0, lower=True, **kwargs):
    k = v.shape[-1]
    n = None
    for cand in range(1, 4096):  # matrix size from packed length
        r, _, _ = _trian_indices(cand, offset, lower)
        if len(r) == k:
            n = cand
            break
        if len(r) > k:
            break
    if n is None:
        raise MXNetError(f"maketrian: no matrix size fits {k} packed "
                         f"entries at offset {offset}")
    rows, cols, _ = _trian_indices(n, offset, lower)

    def f(vv):
        jnp = __import__("jax.numpy", fromlist=["x"])
        out = jnp.zeros(vv.shape[:-1] + (n, n), vv.dtype)
        return out.at[..., rows, cols].set(vv)

    return _registry().apply(f, (v,), name="linalg_maketrian")


# samplers absent from np.random
def random_negative_binomial(k=1, p=0.5, shape=None, dtype=None, ctx=None,
                             out=None, **kwargs):
    """NB(k, p) failure counts via the Gamma-Poisson mixture
    (src/operator/random/sample_op.cc semantics)."""
    import jax

    from .. import random as rng_mod

    shp = (shape,) if isinstance(shape, int) else tuple(shape or ())
    key1, key2 = jax.random.split(rng_mod.as_threefry(rng_mod.next_key()))
    lam = jax.random.gamma(key1, k, shape=shp) * ((1 - p) / p)
    data = jax.random.poisson(key2, lam).astype("float32")
    res = _np().array(data)
    return _write_out(res, out)


def random_generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                         dtype=None, ctx=None, out=None,
                                         **kwargs):
    import jax

    from .. import random as rng_mod

    shp = (shape,) if isinstance(shape, int) else tuple(shape or ())
    key1, key2 = jax.random.split(rng_mod.as_threefry(rng_mod.next_key()))
    if alpha == 0:
        # degenerate: GNB(mu, 0) IS Poisson(mu) (variance mu + alpha mu^2)
        import jax.numpy as jnp

        lam = jnp.full(shp, float(mu))
    else:
        lam = jax.random.gamma(key1, 1.0 / alpha, shape=shp) * (mu * alpha)
    data = jax.random.poisson(key2, lam).astype("float32")
    res = _np().array(data)
    return _write_out(res, out)


def shuffle_legacy(data, **kwargs):
    """Shuffle along the first axis (reference ``_shuffle``), returning a
    new array (np.random.shuffle mutates in place; legacy nd.shuffle
    returns)."""
    out = _np().array(data._data)
    _np().random.shuffle(out)
    return out


def sample_multinomial(data, shape=None, get_prob=False, dtype="int32",
                       **kwargs):
    """Draw class indices from probability rows (reference
    _sample_multinomial): data (..., K) probs -> (..., [shape]) ints."""
    import jax

    from .. import random as rng_mod

    if shape is None:
        draw_shape = (1,)
    elif isinstance(shape, int):
        draw_shape = (shape,)
    else:
        draw_shape = tuple(int(s) for s in shape)
    n = 1
    for s in draw_shape:
        n *= s
    key = rng_mod.next_key()

    def f(d):
        jnp = __import__("jax.numpy", fromlist=["x"])
        logits = jnp.log(jnp.maximum(d, 1e-30))
        out = jax.random.categorical(
            key, logits[..., None, :], axis=-1,
            shape=logits.shape[:-1] + (n,))
        out = out.reshape(logits.shape[:-1] + draw_shape).astype(dtype)
        return out[..., 0] if shape is None else out

    res = _registry().apply(f, (data,), name="sample_multinomial",
                            cacheable=False)
    if get_prob:
        def g(d, idx):
            jnp = __import__("jax.numpy", fromlist=["x"])
            p = jnp.take_along_axis(d[..., None, :],
                                    idx[..., :, None].astype(jnp.int32),
                                    axis=-1)[..., 0]
            return jnp.log(jnp.maximum(p, 1e-30))

        logp = _registry().apply(
            g, (data, res if shape is not None else
                _np().expand_dims(res, -1)), name="sample_multinomial_logp")
        if shape is None:
            logp = _np().squeeze(logp, axis=-1)
        return [res, logp]
    return res


FUNCS = {
    "flatten": flatten,
    "reshape": reshape,
    "arange": arange,
    "cast": cast,
    "slice_legacy": slice_legacy,
    "slice_axis": slice_axis,
    "slice_like": slice_like,
    "slice_channel": slice_channel,
    "broadcast_axis": broadcast_axis,
    "broadcast_axes": broadcast_axes,
    "broadcast_like": broadcast_like,
    "reverse": reverse,
    "add_n": add_n,
    "elemwise_add": elemwise_add,
    "elemwise_mul": elemwise_mul,
    "elemwise_sub": elemwise_sub,
    "elemwise_div": elemwise_div,
    "make_loss": make_loss,
    "shape_array": shape_array,
    "size_array": size_array,
    "argmax_channel": argmax_channel,
    "batch_take": batch_take,
    "smooth_l1": smooth_l1,
    "softmax_cross_entropy": softmax_cross_entropy,
    "softmin": softmin,
    "softsign": softsign,
    "norm": norm,
    "moments": moments,
    "khatri_rao": khatri_rao,
    "all_finite": all_finite,
    "multi_all_finite": multi_all_finite,
    "amp_cast": amp_cast,
    "amp_multicast": amp_multicast,
    "pad_legacy": pad_legacy,
    "upsampling": upsampling,
    "lrn": lrn,
    "erf": erf,
    "rsqrt": rsqrt,
    "rcbrt": rcbrt,
    "digamma": digamma,
    "random_uniform": random_uniform,
    "random_normal": random_normal,
    "random_gamma": random_gamma,
    "random_exponential": random_exponential,
    "random_poisson": random_poisson,
    "random_randint": random_randint,
    "linalg_gemm": linalg_gemm,
    "linalg_gemm2": linalg_gemm2,
    "linalg_syrk": linalg_syrk,
    "linalg_potrf": linalg_potrf,
    "linalg_potri": linalg_potri,
    "linalg_trmm": linalg_trmm,
    "linalg_trsm": linalg_trsm,
    "linalg_gelqf": linalg_gelqf,
    "linalg_det": linalg_det,
    "linalg_slogdet": linalg_slogdet,
    "linalg_inverse": linalg_inverse,
    "linalg_sumlogdiag": linalg_sumlogdiag,
    "linalg_extractdiag": linalg_extractdiag,
    "linalg_makediag": linalg_makediag,
    "linalg_extracttrian": linalg_extracttrian,
    "linalg_maketrian": linalg_maketrian,
    "random_negative_binomial": random_negative_binomial,
    "random_generalized_negative_binomial":
        random_generalized_negative_binomial,
    "sample_multinomial": sample_multinomial,
    "shuffle_legacy": shuffle_legacy,
    "sgd_update": sgd_update,
    "sgd_mom_update": sgd_mom_update,
    "adam_update": adam_update,
    "nag_mom_update": nag_mom_update,
    "signsgd_update": signsgd_update,
    "signum_update": signum_update,
    "rmsprop_update": rmsprop_update,
    "ftrl_update": ftrl_update,
}
def _legacy_cmp_dtype(lhs, rhs):
    dt = getattr(lhs, "dtype", None) or getattr(rhs, "dtype", None)
    return dt if dt is not None else "float32"


def _make_broadcast(tgt):
    def fn(lhs, rhs, out=None, **kwargs):
        res = getattr(_np(), tgt)(lhs, rhs)
        if str(res.dtype) == "bool":
            # the legacy surface returns input-dtype 0/1 floats, not bool
            # (reference broadcast_equal docstring, ndarray/ndarray.py:
            # "array([[ 1.,  1.,  1.], ...], dtype=float32)"); mx.np keeps
            # numpy bool semantics — the cast is legacy-only
            res = res.astype(_legacy_cmp_dtype(lhs, rhs))
        return _write_out(res, out)

    fn.__name__ = tgt
    fn.__doc__ = f"Legacy broadcast op delegating to mx.np.{tgt}"
    return fn


FUNCS.update({name: _make_broadcast(tgt)
              for name, tgt in _BROADCAST_BINARY.items()})

# the elemwise comparison family shares the float-not-bool legacy contract
# (reference ndarray.py ``equal``/``greater``/... docstrings)
FUNCS.update({name: _make_broadcast(tgt) for name, tgt in {
    "equal": "equal", "not_equal": "not_equal",
    "greater": "greater", "greater_equal": "greater_equal",
    "less": "less", "less_equal": "less_equal",
    "logical_and": "logical_and", "logical_or": "logical_or",
    "logical_xor": "logical_xor"}.items()})


def custom(*inputs, op_type=None, **params):
    """Legacy ``nd.Custom`` -> the Python CustomOp registry
    (mx.operator.register; reference src/operator/custom/custom.cc)."""
    from .. import operator as op_mod

    return op_mod.invoke(op_type, *inputs, **params)


FUNCS["Custom"] = custom


# ---------------------------------------------------------------------------
# Deliberate refusals: each resolves to a callable that raises with guidance
# (so the namespace is closed; the Horovod-stub pattern, VERDICT r3 item 6)
# ---------------------------------------------------------------------------
NOT_SUPPORTED = {
    "SoftmaxOutput": "SoftmaxOutput belongs to the removed Module API; use "
                     "npx.softmax for inference and gluon.loss."
                     "SoftmaxCrossEntropyLoss with autograd for training",
    "LinearRegressionOutput": "use gluon.loss.L2Loss with autograd",
    "LogisticRegressionOutput": "use gluon.loss.SigmoidBinaryCrossEntropyLoss",
    "MAERegressionOutput": "use gluon.loss.L1Loss with autograd",
    "IdentityAttachKLSparseReg": "sparsity regularizers are a loss term "
                                 "under autograd; add the KL penalty to "
                                 "your loss explicitly",
    "RNN": "the fused RNN op is exposed through gluon.rnn.{RNN,LSTM,GRU} "
           "(ops/rnn.py rnn_fused); the raw packed-parameter nd.RNN kernel "
           "is not — construct the layer instead",
    "CuDNNBatchNorm": "CUDA-only; nd.BatchNorm lowers to the same XLA op",
    "reset_arrays": "multi-tensor zeroing is XLA's job; assign "
                    "zeros_like per array or use Trainer.zero_grad",
    "multi_sum_sq": "use gluon.Trainer's fused update path; per-array: "
                    "(arr**2).sum()",
    "multi_lars": "LARS runs through optimizer.LARS (fused multi-tensor "
                  "update inside gluon.Trainer)",
    "scatter_set_nd": "alias of scatter_nd with write-inplace; use "
                      "scatter_nd / index_copy",
}
# Refusals that live under nd.contrib / sym.contrib ONLY (they were
# _contrib_* registry names in the reference, never plain-nd names):
CONTRIB_NOT_SUPPORTED = {}
# DGL graph-sampling family (src/operator/contrib/dgl_graph.cc):
# data-dependent output shapes (sampled neighborhoods, compacted graphs)
# have no efficient XLA lowering — graph preprocessing belongs on the
# host, feeding static-shape batches to the device
for _n in ("dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample", "dgl_subgraph",
           "dgl_adjacency", "dgl_graph_compact", "edge_id"):
    CONTRIB_NOT_SUPPORTED[_n] = (
        "DGL graph sampling produces data-dependent shapes; run graph "
        "sampling on the host (e.g. with scipy.sparse) and feed "
        "static-shape index batches to the device ops (take/gather_nd)")
# intgemm (src/operator/contrib/intgemm/): x86 VNNI/AVX512 intrinsics
for _n in ("intgemm_fully_connected", "intgemm_maxabsolute",
           "intgemm_prepare_data", "intgemm_prepare_weight",
           "intgemm_take_weight"):
    CONTRIB_NOT_SUPPORTED[_n] = (
        "intgemm is an x86 SIMD int8 GEMM; the TPU int8 path is "
        "mxnet_tpu.contrib.quantization (native int8 MXU convolutions "
        "and quantized FC)")
for _n in ("multi_sgd_update", "multi_sgd_mom_update", "multi_mp_sgd_update",
           "multi_mp_sgd_mom_update", "preloaded_multi_sgd_update",
           "preloaded_multi_sgd_mom_update", "preloaded_multi_mp_sgd_update",
           "preloaded_multi_mp_sgd_mom_update", "mp_sgd_update",
           "mp_sgd_mom_update", "mp_nag_mom_update", "mp_lamb_update_phase1",
           "mp_lamb_update_phase2", "lamb_update_phase1", "lamb_update_phase2",
           "ftml_update", "rmspropalex_update"):
    NOT_SUPPORTED[_n] = (
        "fused multi-tensor/mixed-precision optimizer kernels run inside "
        "gluon.Trainer's single jitted update (optimizer/optimizer.py); "
        "the raw kernel entry points are not exposed — use the optimizer "
        "classes (mx.optimizer.*)")


def _refusal(name, why):
    def stub(*args, **kwargs):
        raise MXNetError(f"{name} is not supported in the TPU build: {why}")

    stub.__name__ = name
    stub.__doc__ = f"Deliberately unsupported: {why}"
    stub._not_supported = True
    return stub


_MISSING = object()


def _resolve_cascade(name, fallback):
    """Shared ALIASES -> FUNCS -> registry -> ``fallback(target)`` ->
    NOT_SUPPORTED cascade behind both :func:`resolve` (mx.nd surface)
    and :func:`resolve_method` (NDArray methods); ``fallback`` returns
    ``_MISSING`` when it has nothing."""
    target = ALIASES.get(name, name)
    fn = FUNCS.get(target)
    if fn is not None:
        return fn
    reg = _registry()
    try:
        return reg.get(target)
    except MXNetError:
        pass
    fn = fallback(target)
    if fn is not _MISSING:
        return fn
    why = NOT_SUPPORTED.get(name) or NOT_SUPPORTED.get(target)
    if why:
        return _refusal(name, why)
    raise AttributeError(name)


def _np_npx_fallback(target):
    # sentinel, not None: np.newaxis IS None and must resolve to it
    fn = getattr(_np(), target, _MISSING)
    if fn is _MISSING:
        fn = getattr(_npx(), target, _MISSING)
    return fn


def resolve(name):
    """Resolve a legacy op name to an NDArray-level callable, or raise
    AttributeError (so module __getattr__ protocols keep working)."""
    return _resolve_cascade(name, _np_npx_fallback)


# np exports that are genuine elementwise OPERATORS taking the data array
# first — the subset of the mx.np surface the reference C op registry also
# exposes as NDArray methods (``x.exp()``, ``x.log()``...). NDArray
# __getattr__ method resolution is restricted to this closed set plus
# ALIASES/FUNCS/registry (ADVICE r5): namespace utilities (``array``,
# ``zeros``, ``arange``, ...) must NOT become bound methods, and attribute
# typos must raise AttributeError instead of returning nonsense partials.
NDARRAY_METHOD_OPS = frozenset({
    "abs", "absolute", "arccos", "arccosh", "arcsin", "arcsinh", "arctan",
    "arctanh", "cbrt", "ceil", "cos", "cosh", "degrees", "exp", "expm1",
    "fabs", "fix", "floor", "log", "log10", "log1p", "log2", "logical_not",
    "negative", "ones_like", "radians", "reciprocal", "rint", "sign", "sin",
    "sinh", "sqrt", "square", "tan", "tanh", "trunc", "zeros_like",
})


# op-table entries that take no data array first (creation / sampling):
# real ops for the mx.nd surface, nonsense as bound NDArray methods
_NON_METHOD_OPS = frozenset({
    "arange", "random_uniform", "random_normal", "random_gamma",
    "random_exponential", "random_poisson", "random_randint",
    "random_negative_binomial", "random_generalized_negative_binomial",
})


def _curated_fallback(target):
    if target in NDARRAY_METHOD_OPS:
        fn = getattr(_np(), target, _MISSING)
        if fn is not _MISSING:
            return fn
    return _MISSING


def resolve_method(name):
    """Resolve an NDArray method name through the REGISTERED op surface
    only (the shared cascade with the curated elementwise set as its
    fallback instead of the open np/npx surface); AttributeError for
    everything else, so attribute typos surface instead of binding
    arbitrary mx.np exports."""
    if ALIASES.get(name, name) in _NON_METHOD_OPS:
        raise AttributeError(name)
    return _resolve_cascade(name, _curated_fallback)


def _exportable(mod):
    """Non-underscore names of ``mod`` that belong on an op surface —
    skips submodules, exception classes and ``__future__`` features that
    are merely module plumbing (they'd otherwise leak into
    ``mx.nd``/``mx.sym`` ``__dir__``/``__all__``)."""
    import types

    out = set()
    for n in dir(mod):
        if n.startswith("_"):
            continue
        v = getattr(mod, n, None)
        if isinstance(v, types.ModuleType):
            continue
        if isinstance(v, type) and issubclass(v, BaseException):
            continue
        if type(v).__name__ == "_Feature":  # `from __future__ import …`
            continue
        out.add(n)
    return out


def all_names():
    """Every name this surface resolves (for dir() and the parity probe)."""
    names = set(ALIASES) | set(FUNCS) | set(NOT_SUPPORTED)
    names |= _exportable(_np())
    names |= _exportable(_npx())
    names |= set(_registry().list_ops())
    return sorted(names)
