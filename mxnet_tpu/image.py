"""Legacy ``mx.image`` namespace (reference: ``python/mxnet/image/image.py``
over ``src/operator/image/``). Functions operate on HWC uint8/float arrays
or NDArrays; decoding uses PIL (host-side, like the reference's OpenCV)."""
from __future__ import annotations

import io as _io

import numpy as _onp

from .base import MXNetError
from .gluon.data.vision.transforms import (CenterCrop, RandomCrop,
                                           _resize_img, _to_numpy)


def imdecode(buf, flag=1, to_rgb=True, out=None):  # pylint: disable=unused-argument
    """Decode an encoded (jpeg/png) byte string to an HWC NDArray."""
    from PIL import Image

    from . import numpy as mnp

    img = Image.open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = _onp.asarray(img)
    if arr.ndim == 2:
        arr = arr[..., None]
    return mnp.array(arr)


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    from . import numpy as mnp

    return mnp.array(_resize_img(_to_numpy(src), (w, h), interp))


def resize_short(src, size, interp=1):
    """Resize the shorter edge to ``size``, preserving aspect."""
    from . import numpy as mnp

    return mnp.array(_resize_img(_to_numpy(src), size, interp))


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    arr = _to_numpy(src)[y0:y0 + h, x0:x0 + w]
    if size is not None:
        arr = _resize_img(arr, size, interp)
    from . import numpy as mnp

    return mnp.array(arr)


def center_crop(src, size, interp=1):
    arr = _to_numpy(src)
    w_t, h_t = size if isinstance(size, (tuple, list)) else (size, size)
    h, w = arr.shape[:2]
    x0 = (w - w_t) // 2
    y0 = (h - h_t) // 2
    from . import numpy as mnp

    return (mnp.array(CenterCrop((w_t, h_t), interp)(arr)),
            (x0, y0, w_t, h_t))


def random_crop(src, size, interp=1):
    arr = _to_numpy(src)
    w_t, h_t = size if isinstance(size, (tuple, list)) else (size, size)
    h, w = arr.shape[:2]
    if h < h_t or w < w_t:
        arr = _resize_img(arr, (max(w, w_t), max(h, h_t)), interp)
        h, w = arr.shape[:2]
    # crop with the coordinates we return — callers use them for paired
    # label images / bbox adjustment, so they must describe THIS crop
    y0 = _onp.random.randint(0, h - h_t + 1)
    x0 = _onp.random.randint(0, w - w_t + 1)
    from . import numpy as mnp

    return (mnp.array(arr[y0:y0 + h_t, x0:x0 + w_t]),
            (x0, y0, w_t, h_t))


def color_normalize(src, mean, std=None):
    from . import numpy as mnp

    arr = _to_numpy(src).astype(_onp.float32)
    arr = arr - _onp.asarray(mean, dtype=_onp.float32)
    if std is not None:
        arr = arr / _onp.asarray(std, dtype=_onp.float32)
    return mnp.array(arr)


def random_flip_left_right(src, p=0.5):
    arr = _to_numpy(src)
    if _onp.random.rand() < p:
        arr = arr[:, ::-1]
    from . import numpy as mnp

    return mnp.array(arr.copy())


class ImageIter:
    """Legacy augmenting image iterator — delegate to
    ``mxnet_tpu.io.ImageRecordIter`` (same protocol)."""

    def __new__(cls, batch_size, data_shape, path_imgrec=None, **kwargs):
        if path_imgrec is None:
            raise MXNetError("ImageIter requires path_imgrec in this build "
                             "(use gluon.data.vision datasets otherwise)")
        from .io import ImageRecordIter

        return ImageRecordIter(path_imgrec, data_shape,
                               batch_size=batch_size, **kwargs)


def scale_down(src_size, size):
    """Scale ``size`` down proportionally so it fits inside ``src_size``
    (reference ``image.py:scale_down``)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def copyMakeBorder(src, top, bot, left, right, type=0, values=0):  # pylint: disable=redefined-builtin,unused-argument
    """Pad an HWC image with a constant border (reference
    ``image.py:copyMakeBorder`` over cv2.copyMakeBorder; constant mode)."""
    from . import numpy as mnp

    arr = _to_numpy(src)
    out = _onp.pad(arr, ((top, bot), (left, right), (0, 0)),
                   mode="constant", constant_values=values)
    return mnp.array(out)


def random_size_crop(src, size, area, ratio, interp=1, **kwargs):
    """Random crop with size in ``area`` fraction and aspect in ``ratio``,
    resized to ``size`` (reference ``image.py:random_size_crop``)."""
    arr = _to_numpy(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _onp.random.uniform(*area) * src_area
        log_ratio = (_onp.log(ratio[0]), _onp.log(ratio[1]))
        aspect = _onp.exp(_onp.random.uniform(*log_ratio))
        new_w = int(round(_onp.sqrt(target_area * aspect)))
        new_h = int(round(_onp.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = _onp.random.randint(0, w - new_w + 1)
            y0 = _onp.random.randint(0, h - new_h + 1)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    # fallback: center crop (reference behavior)
    out, coords = center_crop(src, size, interp)
    return out, coords


def imrotate(src, rotation_degrees, zoom_in=False, zoom_out=False):
    """Rotate CHW image(s) / NCHW batches by degrees on-device via the
    spatial-transformer ops (reference ``image.py:imrotate`` — same
    float32-only, scalar-angle-for-single-image contract)."""
    from . import numpy as mnp
    from .base import MXNetError
    from .ops.spatial import bilinear_sampler, grid_generator

    if zoom_in and zoom_out:
        raise MXNetError("`zoom_in` and `zoom_out` cannot be both True")
    if str(src.dtype) != "float32":
        raise MXNetError("only float32 images are supported")
    expanded = False
    if src.ndim == 3:
        expanded = True
        src = src.reshape((1,) + tuple(src.shape))
        if hasattr(rotation_degrees, "ndim") and rotation_degrees.ndim:
            raise MXNetError("single image requires a scalar angle")
    elif src.ndim != 4:
        raise MXNetError("only 3D (CHW) and 4D (NCHW) inputs are supported")
    n = src.shape[0]
    ang = _onp.asarray(
        rotation_degrees.asnumpy()
        if hasattr(rotation_degrees, "asnumpy") else rotation_degrees,
        dtype="float32").reshape(-1)
    if ang.size == 1:
        ang = _onp.repeat(ang, n)
    if ang.size != n:
        raise MXNetError("number of angles must match the batch size")
    rad = _onp.pi * ang / 180.0
    c, s = _onp.cos(rad), _onp.sin(rad)
    scale = _onp.ones_like(c)
    if zoom_in:
        scale = 1.0 / (_onp.abs(c) + _onp.abs(s))
    elif zoom_out:
        scale = _onp.abs(c) + _onp.abs(s)
    # output->input mapping: rotate by -theta (positive angle =
    # counterclockwise in image space), scaled
    theta = _onp.stack([c * scale, -s * scale, _onp.zeros(n),
                        s * scale, c * scale, _onp.zeros(n)],
                       axis=1).astype("float32")
    grid = grid_generator(mnp.array(theta), transform_type="affine",
                          target_shape=tuple(src.shape[2:]))
    out = bilinear_sampler(src, grid)
    return out[0] if expanded else out


def random_rotate(src, angle_limits, zoom_in=False, zoom_out=False):
    """Rotate by a uniform random angle in ``angle_limits`` (reference
    ``image.py:random_rotate``)."""
    lo, hi = angle_limits
    if src.ndim == 3:
        ang = float(_onp.random.uniform(lo, hi))
    else:
        ang = _onp.random.uniform(lo, hi, size=(src.shape[0],)) \
            .astype("float32")
    return imrotate(src, ang, zoom_in=zoom_in, zoom_out=zoom_out)


# -- legacy Augmenter family (reference image.py:761-1284) -------------------

class Augmenter:
    """Image augmenter base: callable, with JSON-able params."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        order = _onp.random.permutation(len(self.ts))
        for i in order:
            src = self.ts[i](src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        return random_flip_left_right(src, self.p)


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        from . import numpy as mnp

        alpha = 1.0 + _onp.random.uniform(-self.brightness, self.brightness)
        return mnp.array(_to_numpy(src).astype("float32") * alpha)


class ContrastJitterAug(Augmenter):
    _COEF = _onp.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        from . import numpy as mnp

        arr = _to_numpy(src).astype("float32")
        alpha = 1.0 + _onp.random.uniform(-self.contrast, self.contrast)
        gray = (arr * self._COEF).sum() * 3.0 / arr.size
        return mnp.array(arr * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    _COEF = _onp.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        from . import numpy as mnp

        arr = _to_numpy(src).astype("float32")
        alpha = 1.0 + _onp.random.uniform(-self.saturation, self.saturation)
        gray = (arr * self._COEF).sum(-1, keepdims=True)
        return mnp.array(arr * alpha + gray * (1 - alpha))


class HueJitterAug(Augmenter):
    """Hue rotation in YIQ space (reference ``image.py:1015`` tyiq
    matrices)."""

    _TYIQ = _onp.array([[0.299, 0.587, 0.114],
                        [0.596, -0.274, -0.321],
                        [0.211, -0.523, 0.311]], "float32")
    _ITYIQ = _onp.array([[1.0, 0.956, 0.621],
                         [1.0, -0.272, -0.647],
                         [1.0, -1.107, 1.705]], "float32")

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        from . import numpy as mnp

        arr = _to_numpy(src).astype("float32")
        alpha = _onp.random.uniform(-self.hue, self.hue)
        u, w = _onp.cos(alpha * _onp.pi), _onp.sin(alpha * _onp.pi)
        bt = _onp.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                        "float32")
        t = self._ITYIQ @ bt @ self._TYIQ
        return mnp.array(arr @ t.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-based lighting noise (AlexNet style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _onp.asarray(eigval, "float32")
        self.eigvec = _onp.asarray(eigvec, "float32")

    def __call__(self, src):
        from . import numpy as mnp

        alpha = _onp.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return mnp.array(_to_numpy(src).astype("float32") + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _COEF = _onp.array([[0.299], [0.587], [0.114]], "float32")

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        from . import numpy as mnp

        if _onp.random.rand() < self.p:
            arr = _to_numpy(src).astype("float32")
            gray = arr @ self._COEF
            return mnp.array(_onp.repeat(gray, 3, axis=-1))
        return src if hasattr(src, "_data") else mnp.array(src)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter pipeline (reference
    ``image.py:1171`` — same knobs, same ordering)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _onp.array([55.46, 4.794, 1.148])
        eigvec = _onp.array([[-0.5675, 0.7192, 0.4009],
                             [-0.5808, -0.0045, -0.814],
                             [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = _onp.array([58.395, 57.12, 57.375])
    if mean is not None and len(_onp.atleast_1d(mean)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# -- detection augmenters (reference image/detection.py) ---------------------
# Label convention (reference parity): each object is a row
# [cls_id, xmin, ymin, xmax, ymax, ...], coordinates normalized to [0, 1].

class DetAugmenter:
    """Detection augmenter base: ``__call__(src, label) -> (src, label)``."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Apply an image-only Augmenter, passing labels through (reference
    ``detection.py:66``)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one augmenter from a list, or skip with
    ``skip_prob`` (reference ``detection.py:91``)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or _onp.random.rand() < self.skip_prob:
            return src, label
        aug = self.aug_list[_onp.random.randint(len(self.aug_list))]
        return aug(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and mirror box x-coordinates (reference
    ``detection.py:127``)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        from . import numpy as mnp

        if _onp.random.rand() < self.p:
            src = mnp.array(_to_numpy(src)[:, ::-1].copy())
            label = _onp.array(label, dtype="float32")
            xmin = 1.0 - label[:, 3]
            xmax = 1.0 - label[:, 1]
            label[:, 1], label[:, 3] = xmin, xmax
        return src, label


def _box_area(b):
    return max(0.0, b[2] - b[0]) * max(0.0, b[3] - b[1])


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping sufficient object coverage; objects whose
    center falls outside are dropped, the rest are clipped and
    renormalized (reference ``detection.py:153``)."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _crop_labels(self, label, crop):
        """crop = (x0, y0, x1, y1) normalized; returns adjusted labels or
        None when every object is ejected."""
        x0, y0, x1, y1 = crop
        w, h = x1 - x0, y1 - y0
        out = []
        for row in _onp.array(label, dtype="float32"):
            bx = row[1:5]
            cx, cy = (bx[0] + bx[2]) / 2, (bx[1] + bx[3]) / 2
            if not (x0 <= cx <= x1 and y0 <= cy <= y1):
                continue
            inter = [max(bx[0], x0), max(bx[1], y0),
                     min(bx[2], x1), min(bx[3], y1)]
            area = _box_area(bx)
            if area <= 0 or _box_area(inter) / area \
                    < self.min_eject_coverage:
                continue
            new = row.copy()
            new[1] = (inter[0] - x0) / w
            new[2] = (inter[1] - y0) / h
            new[3] = (inter[2] - x0) / w
            new[4] = (inter[3] - y0) / h
            out.append(new)
        return _onp.stack(out) if out else None

    def __call__(self, src, label):
        from . import numpy as mnp

        arr = _to_numpy(src)
        h, w = arr.shape[:2]
        label = _onp.array(label, dtype="float32")
        for _ in range(self.max_attempts):
            area_f = _onp.random.uniform(*self.area_range)
            ratio = _onp.random.uniform(*self.aspect_ratio_range)
            cw = _onp.sqrt(area_f * ratio)
            ch = _onp.sqrt(area_f / ratio)
            if cw > 1 or ch > 1:
                continue
            cx0 = _onp.random.uniform(0, 1 - cw)
            cy0 = _onp.random.uniform(0, 1 - ch)
            crop = (cx0, cy0, cx0 + cw, cy0 + ch)
            # coverage check: every kept object's overlap fraction
            new_label = self._crop_labels(label, crop)
            if new_label is None:
                continue
            covered = [_box_area([max(b[1], crop[0]), max(b[2], crop[1]),
                                  min(b[3], crop[2]), min(b[4], crop[3])])
                       / max(_box_area(b[1:5]), 1e-12) for b in label]
            if max(covered) < self.min_object_covered:
                continue
            px0, py0 = int(cx0 * w), int(cy0 * h)
            pw, ph = max(1, int(cw * w)), max(1, int(ch * h))
            return (mnp.array(arr[py0:py0 + ph, px0:px0 + pw].copy()),
                    new_label)
        return (src if hasattr(src, "_data") else mnp.array(arr)), label


class DetRandomPadAug(DetAugmenter):
    """Random expand-pad; labels shrink into the padded canvas (reference
    ``detection.py:324``)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        from . import numpy as mnp

        arr = _to_numpy(src)
        h, w = arr.shape[:2]
        label = _onp.array(label, dtype="float32")
        for _ in range(self.max_attempts):
            area_f = _onp.random.uniform(*self.area_range)
            ratio = _onp.random.uniform(*self.aspect_ratio_range)
            nw = int(w * _onp.sqrt(area_f * ratio))
            nh = int(h * _onp.sqrt(area_f / ratio))
            if nw < w or nh < h:
                continue
            x0 = _onp.random.randint(0, nw - w + 1)
            y0 = _onp.random.randint(0, nh - h + 1)
            canvas = _onp.empty((nh, nw, arr.shape[2]), dtype=arr.dtype)
            canvas[:] = _onp.asarray(self.pad_val, dtype=arr.dtype)
            canvas[y0:y0 + h, x0:x0 + w] = arr
            new = label.copy()
            new[:, 1] = (label[:, 1] * w + x0) / nw
            new[:, 2] = (label[:, 2] * h + y0) / nh
            new[:, 3] = (label[:, 3] * w + x0) / nw
            new[:, 4] = (label[:, 4] * h + y0) / nh
            return mnp.array(canvas), new
        return (src if hasattr(src, "_data") else mnp.array(arr)), label


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0.0):
    """One DetRandomSelectAug over per-threshold crop augmenters
    (reference ``detection.py:418`` — each scalar arg may be a list)."""
    # normalize every arg to equal-length lists (reference zips them)
    def aslist(v, like_pairs=False):
        if like_pairs:
            if isinstance(v, tuple):
                return [v]
            return list(v)
        if isinstance(v, (list, tuple)):
            return list(v)
        return [v]

    mocs = aslist(min_object_covered)
    arrs = aslist(aspect_ratio_range, like_pairs=True)
    ars = aslist(area_range, like_pairs=True)
    mecs = aslist(min_eject_coverage)
    mas = aslist(max_attempts)
    n = max(map(len, (mocs, arrs, ars, mecs, mas)))

    def pick(lst, i):
        return lst[i] if i < len(lst) else lst[-1]

    crops = [DetRandomCropAug(pick(mocs, i), pick(arrs, i), pick(ars, i),
                              pick(mecs, i), pick(mas, i))
             for i in range(n)]
    return DetRandomSelectAug(crops, skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0,
                       pca_noise=0, hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection pipeline (reference ``detection.py:483`` —
    same knobs/order: resize, color, pad, crop, mirror, force-resize,
    cast, normalize)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = _onp.array([55.46, 4.794, 1.148])
        eigvec = _onp.array([[-0.5675, 0.7192, 0.4009],
                             [-0.5808, -0.0045, -0.814],
                             [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if rand_pad > 0:
        auglist.append(DetRandomSelectAug(
            [DetRandomPadAug(aspect_ratio_range,
                             (1.0, max(area_range)), max_attempts,
                             pad_val)], 1 - rand_pad))
    if rand_crop > 0:
        auglist.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range,
            (min(area_range), 1.0), min_eject_coverage, max_attempts,
            skip_prob=1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = _onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = _onp.array([58.395, 57.12, 57.375])
    if mean is not None and len(_onp.atleast_1d(mean)):
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter:
    """Detection iterator over an image RecordIO file (reference
    ``detection.py:625``): yields NCHW image batches plus fixed-width
    object-label batches ``(batch, max_objects, label_width)`` padded
    with -1 rows."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 shuffle=False, aug_list=None, label_width=5,
                 max_objects=16, **kwargs):
        if path_imgrec is None:
            raise MXNetError("ImageDetIter requires path_imgrec")
        from .gluon.data.vision.datasets import ImageRecordDataset

        self._dataset = ImageRecordDataset(path_imgrec)
        self.batch_size = batch_size
        self._shape = tuple(data_shape)
        self._shuffle = shuffle
        self._label_width = label_width
        self._max_objects = max_objects
        self.auglist = (aug_list if aug_list is not None
                        else CreateDetAugmenter(data_shape, **kwargs))
        self.reset()

    def reset(self):
        n = len(self._dataset)
        self._order = (_onp.random.permutation(n) if self._shuffle
                       else _onp.arange(n))
        self._pos = 0

    def __iter__(self):
        return self

    def _parse_label(self, raw):
        """Flat record label -> (num_obj, label_width) array (reference
        header format: [header_w, obj_w, ...extras..., obj rows])."""
        raw = _onp.asarray(raw, dtype="float32").ravel()
        if raw.size == self._label_width:
            return raw.reshape(1, self._label_width)
        header_w = int(raw[0])
        obj_w = int(raw[1])
        body = raw[header_w:]
        if body.size % obj_w:
            # reference ImageDetIter raises here: a body that doesn't
            # divide into object rows means a corrupt/mis-written record,
            # and silently dropping the partial object trains on wrong
            # ground truth
            raise MXNetError(
                f"ImageDetIter label body of {body.size} values does not "
                f"divide into obj_width={obj_w} rows (corrupt record?)")
        n = body.size // obj_w
        rows = body.reshape(n, obj_w)
        if obj_w < self._label_width:
            # narrow object rows pad with -1 to label_width (reference
            # pads missing extras rather than shrinking the batch array)
            rows = _onp.concatenate(
                [rows, -_onp.ones((n, self._label_width - obj_w),
                                  rows.dtype)], axis=1)
        return rows[:, :self._label_width]

    def __next__(self):
        from . import numpy as mnp

        if self._pos >= len(self._order):
            raise StopIteration
        idx = self._order[self._pos:self._pos + self.batch_size]
        self._pos += self.batch_size
        c, h, w = self._shape
        data = _onp.zeros((len(idx), c, h, w), dtype="float32")
        labels = -_onp.ones((len(idx), self._max_objects,
                             self._label_width), dtype="float32")
        for k, i in enumerate(idx):
            img, label = self._dataset[int(i)]
            label = self._parse_label(label)
            for aug in self.auglist:
                img, label = aug(img, label)
            arr = _to_numpy(img).astype("float32")
            data[k] = arr.transpose(2, 0, 1)
            m = min(len(label), self._max_objects)
            labels[k, :m] = label[:m]
        return SimpleBatch(mnp.array(data), mnp.array(labels))

    def next(self):
        return self.__next__()


class SimpleBatch:
    """Minimal DataBatch: ``.data``/``.label`` lists (reference
    ``io.DataBatch``)."""

    def __init__(self, data, label):
        self.data = [data]
        self.label = [label]
