"""Legacy ``mx.image`` namespace (reference: ``python/mxnet/image/image.py``
over ``src/operator/image/``). Functions operate on HWC uint8/float arrays
or NDArrays; decoding uses PIL (host-side, like the reference's OpenCV)."""
from __future__ import annotations

import io as _io

import numpy as _onp

from .base import MXNetError
from .gluon.data.vision.transforms import (CenterCrop, RandomCrop,
                                           _resize_img, _to_numpy)


def imdecode(buf, flag=1, to_rgb=True, out=None):  # pylint: disable=unused-argument
    """Decode an encoded (jpeg/png) byte string to an HWC NDArray."""
    from PIL import Image

    from . import numpy as mnp

    img = Image.open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = _onp.asarray(img)
    if arr.ndim == 2:
        arr = arr[..., None]
    return mnp.array(arr)


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    from . import numpy as mnp

    return mnp.array(_resize_img(_to_numpy(src), (w, h), interp))


def resize_short(src, size, interp=1):
    """Resize the shorter edge to ``size``, preserving aspect."""
    from . import numpy as mnp

    return mnp.array(_resize_img(_to_numpy(src), size, interp))


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    arr = _to_numpy(src)[y0:y0 + h, x0:x0 + w]
    if size is not None:
        arr = _resize_img(arr, size, interp)
    from . import numpy as mnp

    return mnp.array(arr)


def center_crop(src, size, interp=1):
    arr = _to_numpy(src)
    w_t, h_t = size if isinstance(size, (tuple, list)) else (size, size)
    h, w = arr.shape[:2]
    x0 = (w - w_t) // 2
    y0 = (h - h_t) // 2
    from . import numpy as mnp

    return (mnp.array(CenterCrop((w_t, h_t), interp)(arr)),
            (x0, y0, w_t, h_t))


def random_crop(src, size, interp=1):
    arr = _to_numpy(src)
    w_t, h_t = size if isinstance(size, (tuple, list)) else (size, size)
    h, w = arr.shape[:2]
    if h < h_t or w < w_t:
        arr = _resize_img(arr, (max(w, w_t), max(h, h_t)), interp)
        h, w = arr.shape[:2]
    # crop with the coordinates we return — callers use them for paired
    # label images / bbox adjustment, so they must describe THIS crop
    y0 = _onp.random.randint(0, h - h_t + 1)
    x0 = _onp.random.randint(0, w - w_t + 1)
    from . import numpy as mnp

    return (mnp.array(arr[y0:y0 + h_t, x0:x0 + w_t]),
            (x0, y0, w_t, h_t))


def color_normalize(src, mean, std=None):
    from . import numpy as mnp

    arr = _to_numpy(src).astype(_onp.float32)
    arr = arr - _onp.asarray(mean, dtype=_onp.float32)
    if std is not None:
        arr = arr / _onp.asarray(std, dtype=_onp.float32)
    return mnp.array(arr)


def random_flip_left_right(src, p=0.5):
    arr = _to_numpy(src)
    if _onp.random.rand() < p:
        arr = arr[:, ::-1]
    from . import numpy as mnp

    return mnp.array(arr.copy())


class ImageIter:
    """Legacy augmenting image iterator — delegate to
    ``mxnet_tpu.io.ImageRecordIter`` (same protocol)."""

    def __new__(cls, batch_size, data_shape, path_imgrec=None, **kwargs):
        if path_imgrec is None:
            raise MXNetError("ImageIter requires path_imgrec in this build "
                             "(use gluon.data.vision datasets otherwise)")
        from .io import ImageRecordIter

        return ImageRecordIter(path_imgrec, data_shape,
                               batch_size=batch_size, **kwargs)
