"""Subgraph backend / graph-pass registry.

Reference: ``src/operator/subgraph/subgraph_property.h:86-385`` — backends
register ``SubgraphProperty`` objects; ``Symbol.optimize_for(backend)``
partitions the graph and hands subgraphs to the backend.

TPU redesign: XLA owns partitioning/fusion, so a "backend" here is a
named bundle of FUNCTION TRANSFORMS applied to the traced forward before
jit — the idiomatic compiler hook on a trace-once runtime. A pass is
``fn -> fn`` (e.g. ``jax.checkpoint`` for rematerialization, a dtype
autocast wrapper, a jaxpr rewriter via ``jax.make_jaxpr``+eval). Backends
compose passes in order.

Built-ins:
* ``remat``   — wrap the forward in ``jax.checkpoint`` (activation
  rematerialization: the memory-planning role of ``PlanMemory``).
* ``bf16``    — cast float inputs/params to bfloat16 for compute (the
  low-precision graph pass, ``src/nnvm/low_precision_pass.cc``).
"""
from __future__ import annotations

from typing import Callable, Dict, List

from .base import MXNetError

_BACKENDS: Dict[str, List[Callable]] = {}


def register_backend(name: str, *passes: Callable):
    """Register (or extend) a backend as an ordered list of fn->fn passes
    (``SubgraphBackendRegistry`` analog)."""
    _BACKENDS.setdefault(name, []).extend(passes)
    return name


def register_pass(backend: str):
    """Decorator form: ``@register_pass('mybackend')``."""

    def deco(fn):
        register_backend(backend, fn)
        return fn

    return deco


def list_backends():
    return sorted(_BACKENDS)


def get_backend_passes(name: str):
    try:
        return list(_BACKENDS[name])
    except KeyError:
        raise MXNetError(
            f"unknown subgraph backend {name!r}; registered: "
            f"{list_backends()}") from None


def apply_backend(name: str, fn: Callable) -> Callable:
    """Compose the backend's passes over a traceable function."""
    for p in get_backend_passes(name):
        fn = p(fn)
    return fn


# -- built-in backends -------------------------------------------------------


def _remat_pass(fn):
    import jax

    return jax.checkpoint(fn)


def _bf16_pass(fn):
    import jax
    import jax.numpy as jnp

    def cast(x):
        try:
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(jnp.bfloat16)
        except TypeError:  # exotic dtypes (PRNG keys)
            pass
        return x

    def wrapped(*args):
        out = fn(*jax.tree_util.tree_map(cast, args))
        return jax.tree_util.tree_map(
            lambda o: o.astype(jnp.float32)
            if hasattr(o, "dtype") and jnp.issubdtype(o.dtype, jnp.floating)
            else o, out)

    return wrapped


register_backend("remat", _remat_pass)
register_backend("bf16", _bf16_pass)
