"""mxnet_tpu — a TPU-native framework with Apache MXNet 2.x capabilities.

Built from scratch on JAX/XLA/Pallas (see SURVEY.md for the structural map of
the reference this follows). Typical use mirrors MXNet::

    import mxnet_tpu as mx
    from mxnet_tpu import np, npx, autograd, gluon

    net = gluon.nn.Dense(10)
    net.initialize(ctx=mx.tpu())
    net.hybridize()                      # trace -> compiled XLA executable
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
"""
from __future__ import annotations

# dtype parity with the reference (INT64_TENSOR_SIZE / float64 ops in the
# numpy op suite) requires 64-bit types enabled in JAX.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

# TPU-first RNG: the rbg generator drives the chip's hardware RNG for bulk
# bits (key management stays threefry), measured 3x faster than the default
# threefry2x32 for dropout-mask generation on v5e (0.10 vs 0.31 ms per
# (64,128,768) bernoulli) — the role cuRAND-philox generator pools play in
# the reference (src/common/random_generator.cu). Override with
# MXNET_RNG_IMPL=threefry2x32 when bitwise key-stream reproducibility across
# backends matters more than speed.
import os as _os

_rng_impl = _os.environ.get("MXNET_RNG_IMPL", "rbg")
if _rng_impl not in ("rbg", "unsafe_rbg", "threefry2x32"):
    raise ImportError(
        f"MXNET_RNG_IMPL={_rng_impl!r} is not a JAX PRNG implementation; "
        "choose rbg, unsafe_rbg or threefry2x32")
_jax.config.update("jax_default_prng_impl", _rng_impl)

from .base import MXNetError, NotSupportedForTPUError, __version__  # noqa: E402
from .device import (  # noqa: E402
    Context,
    Device,
    cpu,
    cpu_pinned,
    current_context,
    current_device,
    gpu,
    gpu_memory_info,
    num_devices,
    num_gpus,
    num_tpus,
    tpu,
)
from . import base  # noqa: E402
from . import device  # noqa: E402
from . import engine  # noqa: E402
from . import autograd  # noqa: E402
from . import random  # noqa: E402
from . import numpy as np  # noqa: E402
from . import ndarray  # noqa: E402
from . import ndarray as nd  # noqa: E402
from . import numpy_extension as npx  # noqa: E402
from .engine import wait_all as waitall  # noqa: E402

context = device  # legacy module alias: mx.context.Context

# MXNET_FAULT_PLAN: install the env-specified fault-injection plan at
# import so its _FAULTS slots are live before the first dispatch (the
# programmatic path is resilience.install_plan). One env read when unset.
if _os.environ.get("MXNET_FAULT_PLAN"):
    from .resilience import faults as _faults

    _faults.get_plan()

# MXNET_LOCKDEP: patch the threading factories at import so every lock
# constructed from here on (sessions, batchers, routers — the instance
# locks the acquisition-order graph is about) is instrumented. Locks
# created before this point (module-level plumbing) stay raw, which
# keeps the sanitizer out of its own bookkeeping.
if _os.environ.get("MXNET_LOCKDEP", "0").strip().lower() not in (
        "", "0", "false"):
    from .resilience import lockdep as _lockdep

    _lockdep.enable()


def cpu_count():
    import os

    return os.cpu_count() or 1


# Heavier subsystems are imported lazily on attribute access so that core
# array use doesn't pay for gluon/model imports (and to keep import cycles
# impossible). ``import mxnet_tpu as mx; mx.gluon`` works either way.
_LAZY_SUBMODULES = (
    "initializer",
    "init",
    "optimizer",
    "lr_scheduler",
    "kvstore",
    "kv",
    "gluon",
    "parallel",
    "profiler",
    "resilience",
    "runtime",
    "util",
    "test_utils",
    "recordio",
    "image",
    "io",
    "operator",
    "library",
    "rtc",
    "amp",
    "dlpack",
    "models",
    "serve",
    "symbol",
    "sym",
    "metric",
    "contrib",
    "config",
    "subgraph",
    "visualization",
    "viz",
    "callback",
    "model",
    "name",
    "attribute",
    "error",
)

_LAZY_ALIASES = {"kv": "kvstore", "sym": "symbol", "init": "initializer",
                 "viz": "visualization"}


def __getattr__(name):
    import importlib

    if name in _LAZY_SUBMODULES:
        target = _LAZY_ALIASES.get(name, name)
        if target == "metric":
            mod = importlib.import_module(".gluon.metric", __name__)
        else:
            mod = importlib.import_module("." + target, __name__)
        globals()[name] = mod
        return mod
    if name in ("set_np", "set_np_shape", "is_np_array", "is_np_shape",
                "use_np", "is_np_default_dtype", "set_np_default_dtype",
                "reset_np"):
        from . import util

        return getattr(util, name)
    raise AttributeError(f"module 'mxnet_tpu' has no attribute {name!r}")
