"""Test utilities (reference: ``python/mxnet/test_utils.py``, 2,602 LoC —
the numeric-comparison and gradient-checking helpers the whole reference
test suite is written against; SURVEY.md §4 keeps (a) numpy-oracle tests,
(b) finite-difference grad checks, (c) cross-backend consistency).
"""
from __future__ import annotations

import numpy as _onp

from .base import MXNetError
from .device import cpu, current_context, num_tpus, tpu

_DTYPE_TOL = {
    _onp.dtype(_onp.float16): (1e-2, 1e-2),
    _onp.dtype(_onp.float32): (1e-4, 1e-5),
    _onp.dtype(_onp.float64): (1e-7, 1e-9),
}


def default_device():
    """Accelerator if present else cpu (reference ``default_context``)."""
    return tpu() if num_tpus() > 0 else cpu()


default_context = default_device


def _to_numpy(a):
    from .ndarray.ndarray import NDArray

    if isinstance(a, NDArray):
        return a.asnumpy()
    return _onp.asarray(a)


def find_max_violation(a, b, rtol, atol):
    """Location + value of the worst |a-b| vs tolerance violation."""
    a, b = _onp.asarray(a, dtype=_onp.float64), _onp.asarray(b, _onp.float64)
    err = _onp.abs(a - b) - (atol + rtol * _onp.abs(b))
    idx = _onp.unravel_index(_onp.argmax(err), err.shape)
    rel = _onp.abs(a - b) / (_onp.abs(b) + atol)
    return idx, float(rel[idx])


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """dtype-aware allclose with a useful max-violation message
    (reference ``test_utils.py:assert_almost_equal``)."""
    a_np, b_np = _to_numpy(a), _to_numpy(b)
    if rtol is None or atol is None:
        dt = _onp.result_type(a_np.dtype, b_np.dtype)
        d_rtol, d_atol = _DTYPE_TOL.get(_onp.dtype(dt), (1e-5, 1e-8))
        rtol = rtol if rtol is not None else d_rtol
        atol = atol if atol is not None else d_atol
    if _onp.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    idx, rel = find_max_violation(a_np, b_np, rtol, atol)
    raise AssertionError(
        f"{names[0]} and {names[1]} differ: max rel-error {rel:.3e} at "
        f"{idx}: {a_np[idx]!r} vs {b_np[idx]!r} (rtol={rtol}, atol={atol})")


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def same(a, b):
    return _onp.array_equal(_to_numpy(a), _to_numpy(b))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    from . import numpy as mnp

    dtype = dtype or _onp.float32
    arr = _onp.random.uniform(-1.0, 1.0, shape).astype(dtype)
    if stype != "default" and density is not None:
        mask = _onp.random.rand(*shape) < density
        arr = arr * mask
    out = mnp.array(arr, ctx=ctx)
    if stype == "row_sparse":
        return out.tostype("row_sparse")
    if stype == "csr":
        return out.tostype("csr")
    return out


def rand_shape_nd(ndim, dim=10):
    return tuple(_onp.random.randint(1, dim + 1, size=ndim).tolist())


def rand_shape_2d(dim0=10, dim1=10):
    return (_onp.random.randint(1, dim0 + 1),
            _onp.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_onp.random.randint(1, dim0 + 1),
            _onp.random.randint(1, dim1 + 1),
            _onp.random.randint(1, dim2 + 1))


def check_numeric_gradient(f, inputs, grads=None, eps=1e-4, rtol=1e-2,
                           atol=1e-4):
    """Finite-difference check of ``f``'s gradients.

    ``f`` maps NDArray inputs to a scalar-reducible NDArray output; the
    analytic gradient comes from autograd, the numeric one from central
    differences (reference ``check_numeric_gradient`` re-done functionally).
    """
    from . import autograd
    from . import numpy as mnp

    arrays = [mnp.array(_to_numpy(x).astype(_onp.float64)) for x in inputs]
    for a in arrays:
        a.attach_grad()
    with autograd.record():
        out = f(*arrays)
        loss = out.sum()
    loss.backward()
    analytic = [a.grad.asnumpy() for a in arrays]

    for i, a in enumerate(arrays):
        base = a.asnumpy()
        num = _onp.zeros_like(base)
        it = _onp.nditer(base, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            pert = base.copy()
            pert[idx] += eps
            plus = float(f(*(arrays[:i] + [mnp.array(pert)]
                             + arrays[i + 1:])).sum().asnumpy())
            pert[idx] -= 2 * eps
            minus = float(f(*(arrays[:i] + [mnp.array(pert)]
                              + arrays[i + 1:])).sum().asnumpy())
            num[idx] = (plus - minus) / (2 * eps)
            it.iternext()
        assert_almost_equal(analytic[i], num, rtol=rtol, atol=atol,
                            names=(f"analytic[{i}]", f"numeric[{i}]"))


def check_consistency(f, inputs, ctx_list=None, rtol=None, atol=None):
    """Run ``f`` on each device and compare outputs — the reference's
    CPU-vs-GPU ``check_consistency`` as CPU-vs-TPU."""
    from . import numpy as mnp

    if ctx_list is None:
        ctx_list = [cpu()] + ([tpu()] if num_tpus() > 0 else [])
    if len(ctx_list) < 2:
        ctx_list = ctx_list * 2  # degenerate: still checks determinism
    outs = []
    for ctx in ctx_list:
        arrs = [mnp.array(_to_numpy(x), ctx=ctx) for x in inputs]
        o = f(*arrs)
        outs.append(_to_numpy(o))
    for o in outs[1:]:
        assert_almost_equal(outs[0], o, rtol=rtol, atol=atol,
                            names=("ctx0", "ctxN"))
    return outs


def numeric_grad(f, x, eps=1e-4):
    """Plain central-difference gradient of scalar f at numpy x."""
    x = _onp.asarray(x, dtype=_onp.float64)
    g = _onp.zeros_like(x)
    it = _onp.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        p = x.copy()
        p[idx] += eps
        m = x.copy()
        m[idx] -= eps
        g[idx] = (f(p) - f(m)) / (2 * eps)
        it.iternext()
    return g


def assert_raises_cudnn_not_satisfied(*a, **k):  # pragma: no cover
    """cuDNN-specific helper kept for API parity; no-op on TPU."""
    import contextlib

    return contextlib.nullcontext()


def assert_exception(fn, exception_type, *args, **kwargs):
    try:
        fn(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(f"{fn} did not raise {exception_type}")


def simple_forward(net, *inputs):
    from . import numpy as mnp

    return net(*[mnp.array(_to_numpy(x)) for x in inputs]).asnumpy()


def environment(*args):
    """Context manager setting env vars for a block (reference
    ``test_utils.environment``)."""
    import contextlib
    import os

    @contextlib.contextmanager
    def _env(pairs):
        saved = {}
        try:
            for k, v in pairs:
                saved[k] = os.environ.get(k)
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = str(v)
            yield
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    if len(args) == 2:
        return _env([(args[0], args[1])])
    return _env(list(args[0].items()))
