"""Device / Context abstraction over JAX devices.

TPU-native analog of the reference's ``python/mxnet/context.py`` (Context over
dev types ``{cpu:1, gpu:2, cpu_pinned:3, cpu_shared:5}``; C++ ``Context`` in
``include/mxnet/base.h``). The TPU build adds ``mx.tpu()`` as the accelerator
device type; ``mx.gpu()`` is kept as an alias for "the accelerator" so that
reference scripts written with ``mx.gpu()`` run unchanged on a TPU host.

A Context maps 1:1 onto a ``jax.Device``; placement is done with
``jax.device_put`` and computation follows operand placement (XLA semantics),
which subsumes the reference's per-device stream/worker machinery
(``src/engine/threaded_engine_perdevice.cc``).
"""
from __future__ import annotations

import threading
from typing import Optional

from .base import MXNetError


def _jax():
    import jax

    return jax


class Context:
    """A device context. Use :func:`cpu`, :func:`tpu`, :func:`gpu` to create.

    Also usable as a ``with`` block to set the default creation context,
    mirroring ``mxnet.Context.__enter__`` (reference ``context.py:139-199``).
    """

    # dev-type enumeration kept value-compatible with the reference
    # (``context.py:65-66``) with ``tpu`` appended as a new type.
    devtype2id = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devid2type = {v: k for k, v in devtype2id.items()}

    _default = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            # copy-ctor form of the reference (``context.py:70-77``):
            # ``Context(mx.gpu(2))`` clones type and id
            device_type, device_id = device_type.device_type, \
                device_type.device_id
        if device_type not in self.devtype2id:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- identity ---------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devtype2id[self.device_type]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- mapping onto JAX devices ----------------------------------------
    def jax_device(self):
        """Resolve this context to a concrete ``jax.Device``."""
        jax = _jax()
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            # local_devices: under multi-process SPMD, jax.devices() is the
            # GLOBAL list and entry 0 may belong to another process — a
            # device_put there would need a collective every process joins
            devs = (jax.local_devices(backend="cpu")
                    if jax.default_backend() != "cpu"
                    else jax.local_devices())
            if self.device_type == "cpu":
                return devs[min(self.device_id, len(devs) - 1)]
            return devs[0]
        # accelerator types: tpu, or gpu-used-as-accelerator-alias
        accel = _accelerator_devices()
        if not accel:
            if self.device_type == "gpu":
                raise MXNetError("no accelerator devices available for gpu()")
            raise MXNetError("no TPU devices available; is JAX seeing the chip?")
        if self.device_id >= len(accel):
            raise MXNetError(
                f"device_id {self.device_id} out of range: "
                f"{len(accel)} accelerator device(s) visible"
            )
        return accel[self.device_id]

    def real_device_type(self) -> str:
        """'tpu' | 'gpu' | 'cpu' of the underlying jax device platform."""
        return self.jax_device().platform

    def empty_cache(self):
        """Release unreferenced device memory (reference ``context.py:120-136``).

        The reference drains its per-device storage pool via
        ``MXStorageEmptyCache``.  Here XLA's allocator owns the pool and
        returns a buffer the moment its last ``jax.Array`` reference dies,
        so the equivalent user-visible action is collecting dropped Python
        references (cycles included) that still pin device buffers.
        """
        import gc
        gc.collect()

    # -- default-context management --------------------------------------
    def __enter__(self):
        if not hasattr(Context._default, "stack"):
            Context._default.stack = []
        Context._default.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default.stack.pop()
        return False

    @classmethod
    def default_ctx(cls) -> "Context":
        stack = getattr(cls._default, "stack", None)
        if stack:
            return stack[-1]
        return _CPU0


# Device is the 2.x name for Context (reference ``python/mxnet/device.py``
# aliases in master); keep both spellings.
Device = Context

_CPU0 = Context("cpu", 0)


def _accelerator_devices():
    jax = _jax()
    if jax.default_backend() == "cpu":
        return []
    return jax.local_devices()


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    """Accelerator alias: on a TPU host this resolves to the TPU chip."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def current_context() -> Context:
    return Context.default_ctx()


current_device = current_context


def num_gpus() -> int:
    """Number of accelerator devices (reference ``mx.context.num_gpus``)."""
    devs = _accelerator_devices()
    return len([d for d in devs if d.platform == "gpu"])


def num_tpus() -> int:
    devs = _accelerator_devices()
    return len([d for d in devs if d.platform != "gpu"])


def num_devices() -> int:
    return len(_jax().devices())


def gpu_memory_info(device_id: int = 0):
    """(free, total) bytes on the accelerator, via PJRT memory stats."""
    dev = tpu(device_id).jax_device() if num_tpus() else gpu(device_id).jax_device()
    stats = dev.memory_stats() or {}
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    return (total - used, total)


def from_jax_device(dev) -> Context:
    """Map a ``jax.Device`` back to a Context."""
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    # accelerator index is its position among accelerator devices
    accel = _accelerator_devices()
    try:
        idx = accel.index(dev)
    except ValueError:
        idx = dev.id
    return Context("tpu" if dev.platform != "gpu" else "gpu", idx)
