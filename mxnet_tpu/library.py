"""Extension loading (reference: ``python/mxnet/library.py`` →
``MXLoadLib``, ``src/c_api/c_api.cc:1491`` — dlopens a C++ library built
against ``include/mxnet/lib_api.h`` to register external ops/passes).

TPU design: external compiled ops target the C ABI of the reference's
engine, which has no analog here — kernels are XLA/Pallas. The supported
extension mechanism is a *Python plugin module* exporting
``register_ops(registry)``; C++ runtime components (e.g. the recordio
scanner in ``native/``) load via ctypes by their own modules."""
from __future__ import annotations

import importlib
import os

from .base import MXNetError, NotSupportedForTPUError


def load(path, verbose=True):
    """Load an extension. ``.py`` modules are imported and their
    ``register_ops(registry)`` hook called; ``.so`` C++ ABI libraries are
    rejected with guidance (no engine C ABI in a TPU build)."""
    if path.endswith(".py"):
        spec = importlib.util.spec_from_file_location(
            os.path.splitext(os.path.basename(path))[0], path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        if hasattr(mod, "register_ops"):
            from .ops import registry

            mod.register_ops(registry)
            if verbose:
                print(f"loaded extension ops from {path}")
        return mod
    raise NotSupportedForTPUError(
        "MXLoadLib loads libraries built against the reference engine's C "
        "ABI (include/mxnet/lib_api.h); this TPU build has no such engine. "
        "Write extensions as Python modules registering JAX-traceable ops "
        "(see mxnet_tpu/ops/registry.py), or as native components with "
        "their own ctypes bindings.")
