"""Network visualization (reference: ``python/mxnet/visualization.py`` —
``print_summary`` layer table and ``plot_network`` graphviz rendering).

Works on this build's lazy :class:`~mxnet_tpu.symbol.Symbol` DAG. For
Gluon models prefer ``Block.summary`` (already implemented); these helpers
cover the symbolic-API parity surface. ``plot_network`` emits DOT source
directly — the ``graphviz`` Python package is optional and only needed to
render to an image.
"""
from __future__ import annotations

from .base import MXNetError


def _walk(symbol):
    """Topological (inputs-first) node order over the Symbol DAG."""
    from .symbol import Symbol

    order, seen = [], set()

    def rec(s):
        if id(s) in seen:
            return
        seen.add(id(s))
        for a in s._args:
            if isinstance(a, Symbol):
                rec(a)
        order.append(s)

    rec(symbol)
    return order


def _node_label(s):
    return s.name or (s._op or "var")


def print_summary(symbol, shape=None, line_length=98, positions=None):
    """Print a layer-by-layer table: name(op), output shape, params,
    previous layers (reference ``visualization.py:print_summary``).

    ``shape``: dict mapping argument names to input shapes (same contract
    as the reference; needed to report per-layer output shapes).
    """
    from .symbol import Symbol

    positions = positions or [0.44, 0.64, 0.74, 1.0]

    def _derive_param_shapes(op, x_shape, kw):
        """Parameter shapes of the layer ops, from the input shape + op
        config — per-position ({arg_index: shape})."""
        import numpy as _np_

        if op == "fully_connected":
            nh = int(kw.get("num_hidden"))
            flat = kw.get("flatten", True)
            in_f = int(_np_.prod(x_shape[1:])) if flat else int(x_shape[-1])
            return {1: (nh, in_f), 2: (nh,)}
        if op == "convolution":
            nf = int(kw.get("num_filter"))
            g = int(kw.get("num_group", 1) or 1)
            kern = tuple(kw.get("kernel") or ())
            return {1: (nf, int(x_shape[1]) // g) + kern, 2: (nf,)}
        if op == "deconvolution":
            nf = int(kw.get("num_filter"))
            g = int(kw.get("num_group", 1) or 1)
            kern = tuple(kw.get("kernel") or ())
            return {1: (int(x_shape[1]), nf // g) + kern, 2: (nf,)}
        if op == "batch_norm":
            ax = int(kw.get("axis", 1))
            c = (int(x_shape[ax]),)
            return {1: c, 2: c, 3: c, 4: c}
        if op in ("layer_norm", "group_norm", "instance_norm"):
            ax = int(kw.get("axis", -1))
            c = (int(x_shape[ax]),)
            return {1: c, 2: c}
        if op == "rms_norm":
            return {1: (int(x_shape[int(kw.get('axis', -1))]),)}
        if op == "embedding":
            return {1: (int(kw.get("input_dim")),
                        int(kw.get("output_dim")))}
        return {}

    order = _walk(symbol)
    shapes = {}
    if shape is not None:
        import numpy as onp

        # ONE evaluation of the DAG on zeros with a shared memo: every
        # node's output shape falls out of the single pass (O(n), not a
        # per-node re-evaluation)
        from . import numpy as mnp

        bindings = {k: mnp.array(onp.zeros(v, "float32"))
                    for k, v in shape.items()}
        # reference-style partial inference: weight/bias/stat shapes of the
        # layer ops are DERIVED from the data shape flowing forward (the
        # role InferShape plays per-op in the reference), so
        # print_summary(sym, shape={'data': ...}) works without listing
        # every parameter
        memo = {}
        for node in order:
            if node._op is None:
                continue
            unbound = [
                (i, a) for i, a in enumerate(node._args)
                if isinstance(a, Symbol) and a._op is None
                and a.name not in bindings]
            if unbound and node._args and isinstance(node._args[0], Symbol):
                x = node._args[0]._eval_with(bindings, memo=memo)
                derived = _derive_param_shapes(
                    node._op, tuple(x.shape), node._kwargs)
                for i, a in unbound:
                    if i in derived:
                        bindings[a.name] = mnp.array(
                            onp.zeros(derived[i], "float32"))
        for node in order:
            if node._op is None and node.name not in bindings:
                raise MXNetError(
                    "shape= must cover every free variable and "
                    "underivable parameter; missing %r" % node.name)
        symbol._eval_with(bindings, memo=memo)
        for node in order:
            out = memo.get(id(node))
            shapes[id(node)] = tuple(out.shape) if out is not None else None

    cols = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def row(fields):
        line = ""
        for text, stop in zip(fields, cols):
            line = (line + str(text))[:stop - 1].ljust(stop)
        print(line)

    print("=" * line_length)
    row(header)
    print("=" * line_length)
    total = 0
    for node in order:
        kind = node._op or "Variable"
        out_shape = shapes.get(id(node), "")
        prev = ", ".join(_node_label(a) for a in node._args
                         if isinstance(a, Symbol))
        # parameter count: given OR derived variable shapes both count
        params = 0
        if node._op is None and shapes.get(id(node)):
            n = 1
            for d in shapes[id(node)]:
                n *= d
            params = n
        total += params
        row(["%s (%s)" % (_node_label(node), kind), out_shape or "",
             params, prev])
        print("_" * line_length)
    print("Total params: %d" % total)
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz digraph of the Symbol DAG (reference
    ``visualization.py:plot_network``). Returns a ``graphviz.Digraph``
    when the package is available, else an object exposing ``.source``
    (DOT text) and ``.save(path)``."""
    from .symbol import Symbol

    node_attrs = node_attrs or {}
    order = _walk(symbol)
    lines = ["digraph \"%s\" {" % title, "  rankdir=BT;"]
    style = ("shape=box, style=filled, fixedsize=false, "
             "fillcolor=\"#8dd3c7\"")
    ids = {}
    for i, node in enumerate(order):
        ids[id(node)] = "node%d" % i
        if node._op is None:
            if hide_weights and node.name not in ("data", "x", "input"):
                continue
            attr = ("shape=oval, style=filled, fillcolor=\"#fb8072\"")
        else:
            attr = style
        extra = "".join(", %s=%s" % kv for kv in node_attrs.items())
        label = _node_label(node)
        if node._op is not None and node._op not in label:
            label = "%s\\n%s" % (label, node._op)
        lines.append("  %s [label=\"%s\", %s%s];"
                     % (ids[id(node)], label, attr, extra))
    for node in order:
        for a in node._args:
            if not isinstance(a, Symbol):
                continue
            if a._op is None and hide_weights \
                    and a.name not in ("data", "x", "input"):
                continue
            lines.append("  %s -> %s;" % (ids[id(a)], ids[id(node)]))
    lines.append("}")
    source = "\n".join(lines)
    try:
        import graphviz  # noqa: F401 — optional renderer

        dot = graphviz.Digraph(name=title, format=save_format)
        dot.body = lines[1:-1]
        return dot
    except ImportError:
        class _Dot:
            def __init__(self, src):
                self.source = src

            def save(self, path):
                with open(path, "w") as f:
                    f.write(self.source)
                return path

            def render(self, *a, **k):
                raise MXNetError(
                    "install the `graphviz` package to render; use "
                    ".source / .save() for the DOT text")

        return _Dot(source)
