"""Network visualization (reference: ``python/mxnet/visualization.py`` —
``print_summary`` layer table and ``plot_network`` graphviz rendering).

Works on this build's lazy :class:`~mxnet_tpu.symbol.Symbol` DAG. For
Gluon models prefer ``Block.summary`` (already implemented); these helpers
cover the symbolic-API parity surface. ``plot_network`` emits DOT source
directly — the ``graphviz`` Python package is optional and only needed to
render to an image.
"""
from __future__ import annotations

from .base import MXNetError


def _walk(symbol):
    """Topological (inputs-first) node order over the Symbol DAG."""
    from .symbol import Symbol

    order, seen = [], set()

    def rec(s):
        if id(s) in seen:
            return
        seen.add(id(s))
        for a in s._args:
            if isinstance(a, Symbol):
                rec(a)
        order.append(s)

    rec(symbol)
    return order


def _node_label(s):
    return s.name or (s._op or "var")


def print_summary(symbol, shape=None, line_length=98, positions=None):
    """Print a layer-by-layer table: name(op), output shape, params,
    previous layers (reference ``visualization.py:print_summary``).

    ``shape``: dict mapping argument names to input shapes (same contract
    as the reference; needed to report per-layer output shapes).
    """
    from .symbol import Symbol

    positions = positions or [0.44, 0.64, 0.74, 1.0]
    order = _walk(symbol)
    shapes = {}
    if shape is not None:
        import numpy as onp

        # ONE evaluation of the DAG on zeros with a shared memo: every
        # node's output shape falls out of the single pass (O(n), not a
        # per-node re-evaluation)
        from . import numpy as mnp

        bindings = {k: mnp.array(onp.zeros(v, "float32"))
                    for k, v in shape.items()}
        for node in order:
            if node._op is None and node.name not in bindings:
                raise MXNetError(
                    "shape= must cover every free variable; missing %r"
                    % node.name)
        memo = {}
        symbol._eval_with(bindings, memo=memo)
        for node in order:
            out = memo.get(id(node))
            shapes[id(node)] = tuple(out.shape) if out is not None else None

    cols = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def row(fields):
        line = ""
        for text, stop in zip(fields, cols):
            line = (line + str(text))[:stop - 1].ljust(stop)
        print(line)

    print("=" * line_length)
    row(header)
    print("=" * line_length)
    total = 0
    for node in order:
        kind = node._op or "Variable"
        out_shape = shapes.get(id(node), "")
        prev = ", ".join(_node_label(a) for a in node._args
                         if isinstance(a, Symbol))
        # parameter count is only known for variables with given shapes
        params = 0
        if node._op is None and shape is not None \
                and node.name in (shape or {}):
            n = 1
            for d in shape[node.name]:
                n *= d
            params = n
        total += params
        row(["%s (%s)" % (_node_label(node), kind), out_shape or "",
             params, prev])
        print("_" * line_length)
    print("Total params: %d" % total)
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz digraph of the Symbol DAG (reference
    ``visualization.py:plot_network``). Returns a ``graphviz.Digraph``
    when the package is available, else an object exposing ``.source``
    (DOT text) and ``.save(path)``."""
    from .symbol import Symbol

    node_attrs = node_attrs or {}
    order = _walk(symbol)
    lines = ["digraph \"%s\" {" % title, "  rankdir=BT;"]
    style = ("shape=box, style=filled, fixedsize=false, "
             "fillcolor=\"#8dd3c7\"")
    ids = {}
    for i, node in enumerate(order):
        ids[id(node)] = "node%d" % i
        if node._op is None:
            if hide_weights and node.name not in ("data", "x", "input"):
                continue
            attr = ("shape=oval, style=filled, fillcolor=\"#fb8072\"")
        else:
            attr = style
        extra = "".join(", %s=%s" % kv for kv in node_attrs.items())
        label = _node_label(node)
        if node._op is not None and node._op not in label:
            label = "%s\\n%s" % (label, node._op)
        lines.append("  %s [label=\"%s\", %s%s];"
                     % (ids[id(node)], label, attr, extra))
    for node in order:
        for a in node._args:
            if not isinstance(a, Symbol):
                continue
            if a._op is None and hide_weights \
                    and a.name not in ("data", "x", "input"):
                continue
            lines.append("  %s -> %s;" % (ids[id(a)], ids[id(node)]))
    lines.append("}")
    source = "\n".join(lines)
    try:
        import graphviz  # noqa: F401 — optional renderer

        dot = graphviz.Digraph(name=title, format=save_format)
        dot.body = lines[1:-1]
        return dot
    except ImportError:
        class _Dot:
            def __init__(self, src):
                self.source = src

            def save(self, path):
                with open(path, "w") as f:
                    f.write(self.source)
                return path

            def render(self, *a, **k):
                raise MXNetError(
                    "install the `graphviz` package to render; use "
                    ".source / .save() for the DOT text")

        return _Dot(source)
