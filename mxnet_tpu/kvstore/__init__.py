"""``mx.kv`` — key-value stores for distributed training (SURVEY.md §2.3)."""
from __future__ import annotations

from .base import KVStoreBase, create
from .kvstore_local import KVStoreDevice, KVStoreLocal
from .dist_tpu import KVStoreDistTPUSync, measure_pushpull_bandwidth
from .gradient_compression import GradientCompression

KVStore = KVStoreBase
