"""BytePS KVStore backend stub (reference ``python/mxnet/kvstore/byteps.py``).

RDMA-optimized parameter server; meaningless on a TPU pod (ICI replaces the
PS fabric). Registered for ABI parity, raises with guidance.
"""
from __future__ import annotations

from ..base import MXNetError
from .base import KVStoreBase


@KVStoreBase.register
class BytePS(KVStoreBase):
    NAME = "byteps"

    def __init__(self):
        raise MXNetError(
            "byteps is not available in this build; on TPU use "
            "kv.create('dist_tpu_sync')")
