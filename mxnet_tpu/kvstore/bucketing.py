"""Gradient/parameter bucketing: size-targeted flat fusion buffers.

Reference: MXNet's ``p3`` priority-sliced propagation and the DeepSpeed/
Horovod fusion-buffer idea — per-parameter collectives are latency-bound
(the llama-8B ZeRO-dp8 step lowered with 1829 all-gathers, one per
param), so the kvstore coalesces tensors into a few ~``bucket_mb``-sized
flat buffers and runs ONE collective per bucket.

The plan is **deterministic**: buckets are packed in parameter
registration order, segregated by dtype (a flat buffer has one dtype)
and by an optional opaque ``group`` key (the ZeRO path uses
``(lr_mult, wd_mult)`` so a whole bucket shares one learning-rate/decay
pair), and the resulting membership depends only on the
``(name, shape, dtype, group)`` sequence — the same model always builds
the same buckets, so bucket shapes are trace-static and the zero
-recompile steady state survives bucketing.

Priorities are front-first (the reference's ``priority=-index`` push
convention): bucket 0 holds the FIRST-registered (front-layer) params
and carries the highest priority, because the next forward consumes
front layers first while backward produced their grads last.

Module-level stats (``bucket_stats()``) are pulled by
``profiler.export.snapshot()`` under the ``kvstore.`` namespace.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Sequence, Tuple

import numpy as _onp

from ..base import MXNetError

MB = 1024 * 1024
# 32 GB of fp32 params / 200 MB ≈ 161 buckets — the "bucket-proportional"
# collective count the ZeRO lowering pin asserts against (≤ 200 for 8B)
DEFAULT_BUCKET_MB = 200.0


class BucketSpec:
    """One flat fusion buffer: which params it holds and where.

    ``names``/``shapes``/``offsets``/``sizes`` are parallel, in
    registration order. ``numel`` is the packed element count; ``total``
    is ``numel`` rounded up to ``pad_multiple`` (the ZeRO path pads to
    the fsdp axis size so the flat buffer shards evenly). ``priority``
    follows the MXNet convention: higher runs first.
    """

    __slots__ = ("index", "names", "shapes", "offsets", "sizes", "dtype",
                 "group", "numel", "total", "priority")

    def __init__(self, index, names, shapes, offsets, sizes, dtype, group,
                 numel, total, priority):
        self.index = index
        self.names = list(names)
        self.shapes = [tuple(s) for s in shapes]
        self.offsets = list(offsets)
        self.sizes = list(sizes)
        self.dtype = _onp.dtype(dtype)
        self.group = group
        self.numel = int(numel)
        self.total = int(total)
        self.priority = int(priority)

    @property
    def key(self):
        return f"__zb{self.index}__"

    @property
    def nbytes(self):
        return self.total * self.dtype.itemsize

    def items(self):
        """Yield ``(name, offset, size, shape)`` per member param."""
        return zip(self.names, self.offsets, self.sizes, self.shapes)

    def __repr__(self):
        return (f"BucketSpec(#{self.index}, {len(self.names)} params, "
                f"{self.numel}/{self.total} {self.dtype}, "
                f"prio={self.priority})")


class GradBucketer:
    """Plans deterministic, dtype-segregated, size-targeted buckets.

    ``bucket_mb=None`` reads ``MXNET_KVSTORE_BUCKET_MB`` (falling back to
    :data:`DEFAULT_BUCKET_MB` when the flag is unset/0 — constructing a
    bucketer means the caller already decided to bucket). ``pad_multiple``
    rounds every bucket's total element count up (the ZeRO flat buffers
    pad to the fsdp axis size so ``P(axis)`` divides them evenly).
    """

    def __init__(self, bucket_mb=None, pad_multiple=1):
        if bucket_mb is None:
            from .. import config as _cfg

            env = float(_cfg.get("MXNET_KVSTORE_BUCKET_MB"))
            bucket_mb = env if env > 0 else DEFAULT_BUCKET_MB
        bucket_mb = float(bucket_mb)
        if not bucket_mb > 0:
            raise MXNetError(
                f"GradBucketer: bucket_mb must be > 0, got {bucket_mb}")
        self.bucket_bytes = int(bucket_mb * MB)
        self.pad_multiple = max(1, int(pad_multiple))

    def plan(self, items: Sequence[Tuple]) -> List["BucketSpec"]:
        """Pack ``(name, shape, dtype[, group])`` items (REGISTRATION
        order) into buckets. Items sharing ``(dtype, group)`` pack
        greedily in order until the next item would overflow
        ``bucket_bytes`` (an item larger than a whole bucket gets its own
        bucket). The final list is ordered by first-member registration
        index — front-layer buckets first — with descending priorities.
        """
        open_buckets: Dict[Tuple, dict] = {}
        closed: List[dict] = []

        def close(b):
            closed.append(b)

        for reg_index, item in enumerate(items):
            if len(item) == 3:
                name, shape, dtype = item
                group = None
            else:
                name, shape, dtype, group = item
            dt = _onp.dtype(dtype)
            size = int(_onp.prod(shape)) if len(tuple(shape)) else 1
            nbytes = size * dt.itemsize
            gkey = (dt.str, group)
            b = open_buckets.get(gkey)
            if b is not None and b["bytes"] + nbytes > self.bucket_bytes \
                    and b["names"]:
                close(b)
                b = None
            if b is None:
                b = {"names": [], "shapes": [], "offsets": [], "sizes": [],
                     "dtype": dt, "group": group, "numel": 0, "bytes": 0,
                     "first": reg_index}
                open_buckets[gkey] = b
            b["names"].append(name)
            b["shapes"].append(tuple(shape))
            b["offsets"].append(b["numel"])
            b["sizes"].append(size)
            b["numel"] += size
            b["bytes"] += nbytes
        for b in open_buckets.values():
            if b["names"]:
                close(b)
        closed.sort(key=lambda b: b["first"])
        specs = []
        pm = self.pad_multiple
        n = len(closed)
        for i, b in enumerate(closed):
            total = -(-b["numel"] // pm) * pm
            specs.append(BucketSpec(
                index=i, names=b["names"], shapes=b["shapes"],
                offsets=b["offsets"], sizes=b["sizes"], dtype=b["dtype"],
                group=b["group"], numel=b["numel"], total=total,
                # front-first: bucket 0 outranks every later bucket
                priority=n - 1 - i))
        return specs


def pack_arrays(spec: BucketSpec, arrays):
    """Concatenate raveled jax arrays (spec order) into the flat buffer,
    zero-padding to ``spec.total``. Trace-safe (static shapes only)."""
    import jax.numpy as jnp

    flats = [a.reshape(-1) for a in arrays]
    if spec.total > spec.numel:
        flats.append(jnp.zeros((spec.total - spec.numel,),
                               dtype=spec.dtype))
    return jnp.concatenate(flats) if len(flats) > 1 else flats[0]


def unpack_flat(spec: BucketSpec, flat):
    """Static slices of the flat buffer back into per-param shapes."""
    return [flat[off:off + size].reshape(shape)
            for _, off, size, shape in spec.items()]


# -- telemetry (profiler.export pulls this under the kvstore. namespace) ----

_STATS_LOCK = threading.Lock()
_STATS = {"bucket_bytes": 0, "buckets_flushed": 0,
          "overlap_window_ms": 0.0}


def record_flush(nbytes, count=1):
    """Count ``count`` flushed buckets carrying ``nbytes`` payload."""
    with _STATS_LOCK:
        _STATS["buckets_flushed"] += int(count)
        _STATS["bucket_bytes"] += int(nbytes)


def record_overlap_window_ms(ms):
    """Accumulate the dispatch-to-wait window (the span in which bucket
    collectives overlap host-side compute under async dispatch)."""
    with _STATS_LOCK:
        _STATS["overlap_window_ms"] += float(ms)


def bucket_stats():
    with _STATS_LOCK:
        return dict(_STATS)


def reset_bucket_stats():
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if k == "overlap_window_ms" else 0
