"""2-bit gradient compression with error feedback — REAL bit packing.

Reference: ``src/kvstore/gradient_compression.{h,cc,cu}``
(``gradient_compression.h:103-121``) — pushes are quantized to 2
bits/value with a residual buffer, cutting PS/DCN bandwidth 16x vs fp32.
The TPU analog packs 4 values per uint8 on-device (jit-friendly shifts),
so what moves over DCN really is the small buffer; over ICI compression is
pointless and the kvstore skips it.

Wire format per value (2 bits): 0 -> 0, 1 -> +threshold, 2 -> -threshold.
"""
from __future__ import annotations

import math

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

_SHIFTS = (0, 2, 4, 6)  # 4 values per byte


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):  # pylint: disable=redefined-builtin
        if type != "2bit":
            raise MXNetError(f"unsupported compression type {type!r}")
        self.type = type
        self.threshold = float(threshold)
        if not self.threshold > 0:
            # threshold=0 quantizes EVERY value to 0 while the residual
            # silently swallows the whole gradient — reject it loudly
            # (reference kvstore.cc accepted it and trained on zeros)
            raise MXNetError(
                f"2bit gradient compression needs threshold > 0, got "
                f"{self.threshold}")
        self._residual = {}
        self._shapes = {}

    # -- dense quantization step (error feedback) -------------------------
    def quantize(self, key, grad: NDArray) -> NDArray:
        """{-threshold, 0, +threshold} with residual accumulation."""
        import jax.numpy as jnp

        res = self._residual.get(key)
        g = grad._data if res is None else grad._data + res
        thr = self.threshold
        q = jnp.where(g >= thr, thr,
                      jnp.where(g <= -thr, -thr, 0.0)).astype(g.dtype)
        self._residual[key] = g - q
        return NDArray(q)

    # -- bit packing ------------------------------------------------------
    def compress(self, key, grad: NDArray) -> NDArray:
        """Quantize (with error feedback) AND pack: returns a uint8 array
        of ceil(n/4) bytes — the buffer that actually travels."""
        import jax.numpy as jnp

        q = self.quantize(key, grad)._data
        thr = self.threshold
        codes = (jnp.where(q > 0, 1, 0) +
                 jnp.where(q < 0, 2, 0)).astype(jnp.uint8).ravel()
        n = codes.shape[0]
        pad = (-n) % 4
        if pad:
            codes = jnp.concatenate(
                [codes, jnp.zeros((pad,), jnp.uint8)])
        nibbles = codes.reshape(-1, 4)
        packed = (
            (nibbles[:, 0] << _SHIFTS[0]) | (nibbles[:, 1] << _SHIFTS[1]) |
            (nibbles[:, 2] << _SHIFTS[2]) | (nibbles[:, 3] << _SHIFTS[3]))
        self._shapes[key] = (grad.shape, str(grad.dtype))
        return NDArray(packed.astype(jnp.uint8))

    def decompress(self, key, compressed: NDArray) -> NDArray:
        """Unpack a compress() buffer back to the dense quantized grad."""
        import jax.numpy as jnp

        if key not in self._shapes:
            raise MXNetError(f"decompress before compress for key {key!r}")
        shape, dtype = self._shapes[key]
        n = int(math.prod(shape)) if shape else 1
        b = compressed._data
        codes = jnp.stack([(b >> s) & 3 for s in _SHIFTS],
                          axis=1).ravel()[:n]
        thr = self.threshold
        vals = jnp.where(codes == 1, thr,
                         jnp.where(codes == 2, -thr, 0.0)).astype(dtype)
        return NDArray(vals.reshape(shape))

    def get_params(self):
        return {"type": self.type, "threshold": self.threshold}
