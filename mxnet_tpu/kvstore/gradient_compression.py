"""2-bit gradient compression with error feedback.

Reference: ``src/kvstore/gradient_compression.{h,cc,cu}`` — quantizes pushes
to 2 bits/value with a residual buffer. On TPU the same transform is a pair
of jitted ops; useful over DCN (cross-slice) links, pointless over ICI.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray.ndarray import NDArray


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):  # pylint: disable=redefined-builtin
        if type != "2bit":
            raise MXNetError(f"unsupported compression type {type!r}")
        self.type = type
        self.threshold = float(threshold)
        self._residual = {}

    def compress(self, key, grad: NDArray) -> NDArray:
        """Quantize to {-threshold, 0, +threshold} with error feedback."""
        import jax.numpy as jnp

        res = self._residual.get(key)
        g = grad._data if res is None else grad._data + res
        thr = self.threshold
        q = jnp.where(g >= thr, thr, jnp.where(g <= -thr, -thr, 0.0)).astype(g.dtype)
        self._residual[key] = g - q
        return NDArray(q)

    def decompress(self, key, compressed: NDArray) -> NDArray:  # pylint: disable=unused-argument
        return compressed

    def get_params(self):
        return {"type": self.type, "threshold": self.threshold}
