"""KVStore plugin ABI (reference ``python/mxnet/kvstore/base.py:74-329``).

``KVStoreBase.register`` string-dispatches backends; the reference ships
``local/device/nccl/dist_sync/...`` in C++ plus Horovod/BytePS Python
plugins. The TPU build's backends:

  * ``local`` / ``device`` — single-process reduce (``kvstore_local.py``)
  * ``dist_tpu_sync`` / ``dist_device_sync`` / ``dist_sync`` — XLA
    collectives over the device mesh (``dist_tpu.py``), replacing the
    ps-lite parameter server (SURVEY.md §3.4 TPU mapping)
  * ``horovod`` / ``byteps`` — present-but-gated stubs
  * ``dist_async`` — raises ``NotSupportedForTPUError`` (no TPU analog)
"""
from __future__ import annotations

from ..base import MXNetError, NotSupportedForTPUError

_BACKENDS = {}


class KVStoreBase:
    """Abstract key-value store for parameter synchronization."""

    OPTIMIZER = "optimizer"

    # -- plugin registry --------------------------------------------------
    @staticmethod
    def register(klass):
        name = getattr(klass, "NAME", klass.__name__).lower()
        _BACKENDS[name] = klass
        return klass

    # -- required API -----------------------------------------------------
    def broadcast(self, key, value, out, priority=0):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        """Reduce ``value`` across devices/workers and (optionally) pull
        into ``out``.

        ``priority`` contract (every backend honors it or rejects it
        loudly — silent ignoring is a bug): a scalar applies to all keys
        and keeps call order; a list/tuple must be exactly 1:1 with the
        grouped keys and settles them by DESCENDING priority (stable),
        so front-of-network gradients — which the next step's forward
        needs first — flush before the tail. A mismatched list raises
        ``MXNetError``."""
        raise NotImplementedError

    @staticmethod
    def is_capable(capability):  # pylint: disable=unused-argument
        return False

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1


def create(name="local", **kwargs):
    """Create a KVStore backend by name (reference ``kvstore.cc:55-85``)."""
    name = name.lower()
    if name == "dist_async" or name == "p3":
        raise NotSupportedForTPUError(
            f"KVStore type {name!r} (asynchronous parameter server) has no "
            "TPU analog: SPMD training over ICI is synchronous by "
            "construction. Use 'dist_tpu_sync'. (SURVEY.md §7 hard-parts 5)")
    # aliases: all dist-sync flavors map to the mesh-collective store
    aliases = {
        "dist_sync": "dist_tpu_sync",
        "dist_device_sync": "dist_tpu_sync",
        "dist": "dist_tpu_sync",
        "nccl": "device",
    }
    name = aliases.get(name, name)
    if name not in _BACKENDS:
        # lazy-import built-in backends
        from . import kvstore_local  # noqa: F401
        from . import dist_tpu  # noqa: F401
        from . import horovod  # noqa: F401
        from . import byteps  # noqa: F401
    try:
        klass = _BACKENDS[name]
    except KeyError:
        raise MXNetError(f"unknown KVStore type {name!r}; "
                         f"registered: {sorted(_BACKENDS)}") from None
    return klass(**kwargs)
