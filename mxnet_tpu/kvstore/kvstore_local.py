"""Local / device KVStore: single-process multi-device data parallelism.

Reference: ``src/kvstore/kvstore_local.h`` + ``Comm`` reduce strategies
(``comm.h`` CPU/Device/Tree). On TPU a cross-device reduce is one fused XLA
computation (device_put + add), so CommCPU/CommDevice/CommDeviceTree
collapse into this class; topology-aware trees (``gpu_topology.h``) are the
XLA runtime's problem, not ours.

Also implements ``update_on_kvstore`` semantics: ``set_optimizer`` installs
an :class:`~mxnet_tpu.optimizer.Updater` applied at pushpull time, matching
the reference server-side optimizer path.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..optimizer import Updater, create as create_optimizer
from .base import KVStoreBase


def _sum_values(values):
    if len(values) == 1:
        return values[0].copy()
    import jax

    first = values[0]
    dev = list(first._data.devices())[0]
    total = first._data
    for v in values[1:]:
        total = total + jax.device_put(v._data, dev)
    return NDArray(total)


@KVStoreBase.register
class KVStoreLocal(KVStoreBase):
    NAME = "local"

    def __init__(self):
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None
        # settle-order telemetry: (key, priority) per flushed key, most
        # recent last — the priority regression tests read it
        self._flush_log = []

    # -- legacy init/push/pull API (reference kvstore.h) ------------------
    def init(self, key, value):
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            self._store[k] = v.copy() if isinstance(v, NDArray) else NDArray(v)

    def push(self, key, value, priority=0):  # pylint: disable=unused-argument
        keys, values = _normalize_grouped(key, value)
        for k, vals in zip(keys, values):
            reduced = _sum_values(vals)
            if self._updater is not None and k in self._store:
                self._updater(_int_key(k), reduced, self._store[k])
            elif k in self._store:
                self._store[k] += reduced
            else:
                self._store[k] = reduced

    def pull(self, key, out=None, priority=0, ignore_sparse=True):  # pylint: disable=unused-argument
        keys, outs = _normalize_grouped(key, out)
        for k, dsts in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized in kvstore")
            src = self._store[k]
            for d in dsts:
                src.copyto(d)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """O(nnz) row pull (``PullRowSparse``, include/mxnet/kvstore.h:161):
        only the requested rows move; a row_sparse destination adopts
        (row_ids, rows) buffers directly — the (vocab, dim) dense view is
        never built."""
        keys, outs = _normalize_grouped(key, out)
        _, rids = _normalize_grouped(key, row_ids)
        for k, dsts, rid in zip(keys, outs, rids):
            src = self._store[k]
            for d, r in zip(dsts, rid):
                rows = r.astype("int64")
                picked = src._data[rows._data]  # axis-0 row gather, O(nnz)
                if d.stype == "row_sparse":
                    from ..ndarray.ndarray import NDArray
                    from ..ndarray.sparse import RowSparseNDArray

                    d._set_sparse(RowSparseNDArray(
                        NDArray(picked), rows, d.shape))
                else:
                    d._set_data_internal(picked)

    def pushpull(self, key, value, out=None, priority=0):
        """Push-then-pull per key. ``priority`` is honored (reference
        ``p3`` semantics, higher first): a scalar applies to every key; a
        list/tuple must be 1:1 with the grouped keys and orders the
        flushes by DESCENDING priority (stable — equal priorities keep
        call order), so front-layer grads settle before the tail."""
        keys, values = _normalize_grouped(key, value)
        _, outs = _normalize_grouped(key, out)
        for idx, prio in _priority_order(keys, priority):
            k = keys[idx]
            self.push(k, values[idx])
            if outs[idx] is not None:
                self.pull(k, outs[idx])
            self._record_flush(k, prio)

    def _record_flush(self, k, prio):
        self._flush_log.append((k, prio))
        if len(self._flush_log) > 4096:
            del self._flush_log[:2048]

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out, priority)

    # -- optimizer-on-store ----------------------------------------------
    def set_optimizer(self, optimizer):
        self._optimizer = (create_optimizer(optimizer)
                           if isinstance(optimizer, str) else optimizer)
        self._updater = Updater(self._optimizer)

    def set_gradient_compression(self, compression_params):
        from .gradient_compression import GradientCompression

        self._compression = GradientCompression(**compression_params)

    @staticmethod
    def is_capable(capability):
        return capability == KVStoreBase.OPTIMIZER

    # -- cluster shape ----------------------------------------------------
    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    @property
    def type(self):
        return self.NAME

    def barrier(self):
        from .. import engine

        engine.wait_all()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


@KVStoreBase.register
class KVStoreDevice(KVStoreLocal):
    """'device' store: reduce on accelerator (same fused path on TPU)."""

    NAME = "device"


def _normalize(key, value):
    if isinstance(key, (list, tuple)):
        return list(key), list(value)
    return [key], [value]


def _normalize_grouped(key, value):
    """Return keys plus list-of-lists of values per key."""
    if isinstance(key, (list, tuple)):
        keys = list(key)
        if value is None:
            return keys, [None] * len(keys)
        vals = []
        for v in value:
            vals.append(list(v) if isinstance(v, (list, tuple)) else [v])
        return keys, vals
    if value is None:
        return [key], [None]
    return [key], [list(value) if isinstance(value, (list, tuple)) else [value]]


def _int_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _priority_order(keys, priority):
    """Flush order for grouped keys: ``[(index, priority), ...]`` sorted
    by DESCENDING priority, stable. A scalar priority keeps call order; a
    per-key list must match the key count — anything else is loudly
    rejected (the reference silently ignored the argument)."""
    if isinstance(priority, (list, tuple)):
        if len(priority) != len(keys):
            raise MXNetError(
                f"pushpull: got {len(priority)} priorities for "
                f"{len(keys)} keys — pass one int per key (or a single "
                "scalar for all)")
        prios = [int(p) for p in priority]
    else:
        prios = [int(priority)] * len(keys)
    order = sorted(range(len(keys)), key=lambda i: -prios[i])
    return [(i, prios[i]) for i in order]
