"""``dist_tpu_sync``: the TPU-native distributed KVStore.

This is the BASELINE.json north-star component: it replaces the reference's
ps-lite parameter-server push/pull (``src/kvstore/kvstore_dist.h`` workers ↔
``kvstore_dist_server.h`` servers over a ZMQ van) with XLA collectives over
ICI/DCN. There are no scheduler/server roles: every process is an SPMD
worker (``jax.distributed``), and ``pushpull`` is a compiled ``psum``.

Mapping (SURVEY.md §3.4):
  worker local reduce (Comm)        -> part of the same jitted psum
  ZPushPull to sharded servers      -> all-reduce over the mesh 'dp' axis
  server ApplyUpdates (sync wait)   -> collective is the barrier
  EncodeDefaultKey sharding         -> reduce_scatter option (ZeRO-style)

Two operating modes:
  * replicated arrays (one per device / per-process): ``pushpull`` jit-psums
    the stack — used by ``gluon.Trainer`` for MXNet-style per-device lists.
  * mesh-sharded ``jax.Array``s (the native path): grads computed inside a
    ``pjit`` with a sharded batch axis already arrive reduced; pushpull is
    then an identity with sharding assertions (XLA inserted the collective).
"""
from __future__ import annotations

import warnings
import weakref

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..profiler import core as _prof
from ..profiler import recorder as _recorder
from ..profiler import trace as _trace
from ..resilience import counters as _res_counters
from ..resilience import retry as _retry
from .base import KVStoreBase
from .kvstore_local import KVStoreLocal, _normalize_grouped, _priority_order

# fault-injection hot-state (resilience.faults.FaultPlan slot, see
# ops/registry.py): None until a plan installs
_FAULTS = None

# straggler-monitor hot-state (resilience.elastic.StragglerMonitor slot,
# same discipline): None until a monitor installs; when set, collective
# call sites report per-replica arrival lag to it
_STRAGGLER = None

# live stores, for the process-wide collective_stats() aggregate
# (profiler.export pulls it); weak so the registry never pins a store
_stores: "weakref.WeakSet" = weakref.WeakSet()


def _tag_step(args):
    """Attach the current training-step id (profiler.trace.set_step) to a
    collective event's args so a dumped trace correlates collectives with
    the estimator's train::step spans."""
    if _trace.ENABLED:
        args["step"] = _trace.current_step()
    return args


def collective_stats():
    """Process-wide collective telemetry: per-instance ``_stats`` fields
    summed over every live store, plus the worst breaker state ('open' >
    'half_open' > 'closed') and the shared retry/watchdog counters."""
    rank = {"closed": 0, "half_open": 1, "open": 2}
    # compressed_bytes_saved is seeded so the gauge exists (at 0) even
    # after every store is collected — dashboards key on its presence
    agg = {"stores": 0, "breaker_state": "closed",
           "compressed_bytes_saved": 0}
    for kv in list(_stores):
        agg["stores"] += 1
        for k, v in kv._stats.items():
            agg[k] = agg.get(k, 0) + v
        state = kv._breaker.snapshot().get("state", "closed")
        if rank.get(state, 0) > rank[agg["breaker_state"]]:
            agg["breaker_state"] = state
    agg["retries"] = _res_counters.get("resilience.retries")
    agg["watchdog_timeouts"] = _res_counters.get(
        "resilience.watchdog_timeouts")
    agg["watchdog_orphans"] = _retry.watchdog_orphans()
    return agg


def _jax():
    import jax

    return jax


@KVStoreBase.register
class KVStoreDistTPUSync(KVStoreLocal):
    NAME = "dist_tpu_sync"

    def __init__(self, mesh=None, axis="dp"):
        super().__init__()
        from ..parallel import mesh as mesh_mod

        self._mesh = mesh if mesh is not None else mesh_mod.get_mesh(create=True)
        self._axis = axis if (self._mesh is None or axis in self._mesh.axis_names) \
            else self._mesh.axis_names[0]
        self._allreduce_jit = {}      # (shape, dtype) -> AOT-compiled psum
        self.last_path = None         # 'collective' | 'eager' (tests assert)
        self.last_hlo = None          # compiled HLO of the last collective
        self.last_error = None        # why the fast path last degraded
                                      # ("ExcType: msg" string, never the
                                      # live exception — see
                                      # _record_degradation)
        from .. import config as _config

        # resilience: after K consecutive fast-path failures stop trying
        # the collective (straight to eager) until the cooldown lets one
        # half-open probe through (resilience.retry.CircuitBreaker)
        self._breaker = _retry.CircuitBreaker(
            failure_threshold=_config.get(
                "MXNET_COLLECTIVE_BREAKER_THRESHOLD"),
            cooldown_calls=_config.get(
                "MXNET_COLLECTIVE_BREAKER_COOLDOWN"),
            name="kvstore.allreduce")
        # retry policy + watchdog timeout resolved ONCE here, like the
        # breaker thresholds above: allreduce runs per training step and
        # must not re-read the environment per call (fault plans, by
        # contrast, can be installed/cleared at any time — the _FAULTS
        # slot is re-poked, not re-read)
        self._retry_policy = _retry.collective_policy()
        self._watchdog_timeout = _retry.collective_timeout()
        # pre-collective NaN quarantine (resilience.guardrails): resolved
        # once here like the knobs above — allreduce runs per step
        self._nan_quarantine = bool(_config.get("MXNET_NAN_QUARANTINE"))
        self._nan_quarantine_mode = str(
            _config.get("MXNET_NAN_QUARANTINE_MODE"))
        if self._nan_quarantine_mode not in ("skip", "drop"):
            # a typo ('Drop') would otherwise silently behave as skip
            raise MXNetError(
                f"MXNET_NAN_QUARANTINE_MODE must be 'skip' or 'drop', "
                f"got {self._nan_quarantine_mode!r}")
        # elastic mesh-loss classification (resilience.elastic): resolved
        # once like the knobs above. Off (default): a lost chip degrades
        # to the eager fallback exactly like any fatal fast-path failure
        # (the PR-2 semantics, regression-pinned); on: it raises
        # MeshDegraded so an ElasticTrainingHandler can shrink the mesh
        # and resume from checkpoint instead of training through a
        # half-dead collective.
        self._elastic = bool(_config.get("MXNET_ELASTIC"))
        # 2-bit gradient compression (MXNET_GRADIENT_COMPRESSION=2bit, off
        # by default; Trainer's compression_params wires the same slot via
        # set_gradient_compression). Over ICI the fabric outruns the
        # quantize kernel, so this reproduces the reference's compressed
        # DCN ZPushPull *numerics* (error feedback, bounded divergence)
        # rather than saving on-chip bytes — see _maybe_compress.
        comp_type = str(_config.get("MXNET_GRADIENT_COMPRESSION") or "")
        if comp_type.strip():
            from .gradient_compression import GradientCompression

            self._compression = GradientCompression(type=comp_type.strip())
        self._stats = {"allreduce_calls": 0, "collective": 0, "eager": 0,
                       "degradations": 0, "breaker_skips": 0,
                       "quarantined": 0, "mesh_losses": 0,
                       "compressed_bytes_saved": 0}
        _stores.add(self)

    def collective_stats(self):
        """Resilience/degradation telemetry for this store (the
        ``cache_stats()`` analog): path counts, why the fast path last
        degraded, breaker state, process-wide retry counters."""
        out = dict(self._stats)
        out["breaker"] = self._breaker.snapshot()
        out["last_error"] = self.last_error
        out["retries"] = _res_counters.get("resilience.retries")
        out["watchdog_timeouts"] = _res_counters.get(
            "resilience.watchdog_timeouts")
        # abandoned watchdog bodies (still-running orphans can mutate
        # state behind the fast path — operator signal, not noise)
        out["watchdog_orphans"] = _retry.watchdog_orphans()
        return out

    def _classify_mesh_loss(self, exc, op="allreduce"):
        """Elastic classification (``MXNET_ELASTIC=1`` only): is this
        collective failure a *lost device group* rather than a transient?
        Returns a ready-to-raise :class:`~..resilience.elastic.
        MeshDegraded` (counted + traced) or ``None`` for everything
        else (which then takes the PR-2 degrade-to-eager path)."""
        from ..resilience import elastic as _elastic

        if not _elastic.is_mesh_loss(exc):
            return None
        lost = getattr(exc, "replica", None)
        lost = [int(lost)] if lost is not None else None
        # coordinate-addressed chip loss (composed dp×tp meshes): forward
        # the device address so the elastic layer can rebuild_mesh on it
        device = getattr(exc, "device", None)
        return self._mesh_degraded(
            lost, f"{type(exc).__name__}: {exc}", op,
            lost_devices=[device] if device is not None else None)

    def _mesh_degraded(self, lost, cause, op, lost_devices=None):
        """Count + trace + warn one mesh-loss event and build the
        :class:`MeshDegraded` to raise (shared by exception
        classification and the breaker-open device probe)."""
        from ..resilience import elastic as _elastic

        self._stats["mesh_losses"] += 1
        _res_counters.incr("resilience.mesh_losses")
        if _prof.ENABLED:
            _prof.record_instant(f"resilience::mesh_loss({op})",
                                 "resilience",
                                 args={"lost": lost,
                                       "error": str(cause)[:200]})
        # crash forensics: the moments before a mesh loss, on disk
        _recorder.dump("mesh_degraded",
                       args={"op": op, "lost": lost,
                             "lost_devices": lost_devices,
                             "cause": str(cause)[:500],
                             "step": _trace.current_step()})
        warnings.warn(
            f"kvstore {op}: collective failure classified as MESH LOSS "
            f"(lost replica(s) {lost if lost is not None else 'unknown'}): "
            f"{cause} — raising MeshDegraded for elastic recovery",
            RuntimeWarning, stacklevel=4)
        return _elastic.MeshDegraded(
            f"{op} lost part of the mesh: {cause}",
            lost_replicas=lost,
            mesh_size=self._mesh.size if self._mesh is not None else None,
            lost_devices=lost_devices)

    def _probe_lost_devices(self):
        """Tiny device_put + blocking read against every mesh device;
        returns the indices that FAILED. Runs only on the elastic
        breaker-open path — while the breaker skips the fast path there
        is no collective attempt to throw a classifiable error, and a
        chip that dies during the cooldown would otherwise be summed as
        a stale buffer by the eager fallback, silently, forever."""
        import jax
        import jax.numpy as jnp

        lost = []
        for i, dev in enumerate(self._mesh_devices()):
            try:
                jax.device_put(jnp.ones((1,), jnp.float32),
                               dev).block_until_ready()
            except Exception:  # noqa: BLE001 — any failure = dead
                lost.append(i)
        return lost

    def _record_degradation(self, exc, op="allreduce"):
        """Satellite fix: the fast path must not degrade silently — keep
        the cause on ``last_error``, count it, and warn (rate-limited to
        powers of ten so a degraded steady state doesn't spam one warning
        per step)."""
        # formatted, not the live exception: exc.__traceback__ would pin
        # the failed attempt's frames (and the per-device gradient
        # buffers they reference) for the life of the store
        self.last_error = f"{type(exc).__name__}: {exc}"
        self._stats["degradations"] += 1
        n = self._stats["degradations"]
        _res_counters.incr("resilience.degradations")
        if _prof.ENABLED:
            _prof.record_instant(f"resilience::degradation({op})",
                                 "resilience",
                                 args={"error": f"{type(exc).__name__}: "
                                                f"{exc}"[:200]})
        if _res_counters.should_warn(n):
            warnings.warn(
                f"kvstore {op} collective fast path degraded to the eager "
                f"fallback ({n}x so far): {type(exc).__name__}: {exc} — "
                "see collective_stats() for breaker state",
                RuntimeWarning, stacklevel=3)

    # -- cluster shape ----------------------------------------------------
    @property
    def rank(self):
        return _jax().process_index()

    @property
    def num_workers(self):
        return _jax().process_count()

    @property
    def num_devices(self):
        return self._mesh.size if self._mesh is not None else len(_jax().devices())

    @property
    def type(self):
        return self.NAME

    def barrier(self):
        """Reference: ps-lite Barrier. Here: a tiny psum over the mesh.

        Runs under the ``MXNET_COLLECTIVE_TIMEOUT`` watchdog and fires the
        ``collective:barrier`` fault site — a barrier is the one
        collective every worker blocks on unconditionally, so a hung one
        (dead peer, partitioned ring) used to be the one place the
        runtime could still wait forever un-instrumented. A timeout
        surfaces as :class:`~..resilience.retry.CollectiveTimeoutError`
        with the usual orphan accounting."""
        if self._mesh is None:
            return
        flt = _FAULTS
        if flt is not None:
            # a 'delay' rule here + MXNET_COLLECTIVE_TIMEOUT exercises the
            # hung-barrier watchdog deterministically; the sleep must be
            # INSIDE the watched body or the watchdog would never see it
            def body(mesh=self._mesh):
                flt.check("collective:barrier", {"size": mesh.size})
                return self._barrier_psum(mesh)
        else:
            def body(mesh=self._mesh):
                return self._barrier_psum(mesh)
        _retry.run_with_watchdog(body, self._watchdog_timeout,
                                 site="kvstore::barrier")

    @staticmethod
    def _barrier_psum(mesh):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(
            jnp.ones((mesh.size,), jnp.int32),
            NamedSharding(mesh, P(mesh.axis_names)))
        total = jax.jit(
            lambda v: v.sum(), out_shardings=NamedSharding(mesh, P()))(x)
        total.block_until_ready()

    def _quarantine_check(self, arrays, datas):
        """Pre-collective NaN quarantine (``MXNET_NAN_QUARANTINE=1``): a
        non-finite gradient is caught BEFORE the collective, because after
        the allreduce every replica on the mesh carries the poison.

        Returns ``None`` when every replica is finite. On trip:
        ``mode='skip'`` raises :class:`~...resilience.guardrails.
        NonFiniteGradError` (the estimator's GuardrailHandler turns it
        into a skipped step); ``mode='drop'`` excludes the poisoned
        replicas and returns the sum of the clean ones rescaled by
        ``n_total/n_clean`` — the unbiased estimate of the full-mesh sum,
        placed back on every source device.
        """
        import jax
        import jax.numpy as jnp

        bad = [not bool(jnp.isfinite(d).all()) for d in datas]
        if not any(bad):
            return None
        from ..resilience.guardrails import NonFiniteGradError

        n, nbad = len(datas), sum(bad)
        self._stats["quarantined"] += 1
        _res_counters.incr("resilience.nan_quarantined")
        if _prof.ENABLED:
            _prof.record_instant("resilience::quarantine(allreduce)",
                                 "resilience",
                                 args={"bad_replicas": nbad, "of": n,
                                       "mode": self._nan_quarantine_mode})
        warnings.warn(
            f"NaN quarantine: {nbad}/{n} gradient replica(s) non-finite "
            f"before the allreduce (mode={self._nan_quarantine_mode})",
            RuntimeWarning, stacklevel=3)
        if self._nan_quarantine_mode == "drop" and nbad < n:
            good = [d for d, b in zip(datas, bad) if not b]
            dev0 = next(iter(good[0].devices()))
            stacked = jnp.stack([jax.device_put(d, dev0) for d in good])
            summed = jnp.sum(stacked, axis=0) * (n / len(good))
            return [NDArray(jax.device_put(
                summed, list(a._data.devices())[0])) for a in arrays]
        if nbad == n:
            raise NonFiniteGradError(
                f"allreduce quarantine: every replica ({n}/{n}) contains "
                "NaN/Inf — nothing to sum in any mode. Skip this step "
                "(GuardrailHandler does this automatically) or attach a "
                "LossScaler so overflows are absorbed pre-collective.")
        raise NonFiniteGradError(
            f"allreduce quarantine: {nbad}/{n} gradient replica(s) "
            "contain NaN/Inf — the collective would poison every replica "
            "on the mesh. Skip this step (GuardrailHandler does this "
            "automatically), or set MXNET_NAN_QUARANTINE_MODE=drop to "
            "sum the clean replicas only.")

    # -- collectives ------------------------------------------------------
    def _mesh_devices(self):
        return list(self._mesh.devices.flatten()) if self._mesh is not None \
            else []

    def _get_allreduce_jit(self, shape, dtype, sample):
        """AOT-compiled `sum over the device axis -> replicated`: one XLA
        all-reduce over ICI (the role of ZPushPull + server ApplyUpdates,
        `src/kvstore/kvstore_dist.h:578` / `kvstore_dist_server.h:346`)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (tuple(shape), str(dtype))
        hit = self._allreduce_jit.get(key)
        if hit is not None:
            return hit
        mesh = self._mesh
        # stack dim 0 is one-entry-per-mesh-device: shard it over ALL mesh
        # axes (a dp×tp mesh reduces over the whole device set, matching
        # the reference's global PushPull)
        jitted = jax.jit(
            lambda s: s.sum(axis=0),
            in_shardings=NamedSharding(
                mesh, P(tuple(mesh.axis_names), *([None] * len(shape)))),
            out_shardings=NamedSharding(mesh, P()),
        )
        t0 = _prof.begin() if _prof.ENABLED else 0

        def compile_fn():
            flt = _FAULTS
            if flt is not None:
                flt.check("kvstore:allreduce_compile",
                          {"shape": tuple(shape)})
            return jitted.lower(sample).compile()

        # transient compile failures (tunnel drop, concurrent-compile
        # RESOURCE_EXHAUSTED) back off and retry; real lowering errors
        # re-raise on the first attempt
        compiled = _retry.call_with_retry(
            compile_fn, site="kvstore::allreduce_compile",
            policy=_retry.compile_policy())
        if t0:
            # the AOT-compile half of the compile-vs-execute split: one
            # event per (shape, dtype), execute timing lives in allreduce
            _prof.record_duration("kvstore::allreduce_compile", "kvstore",
                                  t0, args={"shape": list(shape),
                                            "dtype": str(dtype)})
        self.last_hlo = compiled.as_text()
        self._allreduce_jit[key] = compiled
        return compiled

    def _collective_allreduce(self, datas):
        """Fast path: per-device arrays assembled zero-copy into one array
        sharded over the mesh axis, reduced by the compiled psum. Returns
        None when the list doesn't line up 1:1 with the mesh devices (then
        the eager fallback handles it)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        flt = _FAULTS
        if flt is not None:
            # per-ATTEMPT injection point: a 'transient' rule here is what
            # the retry wrapper in allreduce() recovers from; a 'delay'
            # rule simulates the stuck collective the watchdog bounds; a
            # 'chip_loss' rule raises ChipLostError (a dead device group
            # — classified as mesh loss by allreduce() when elastic is
            # on); a 'replica_delay' rule models one replica arriving
            # late at the collective — the lag is reported to the
            # straggler monitor below
            mk = flt.check("kvstore:allreduce", {"n": len(datas)})
            if isinstance(mk, dict) and mk.get("kind") == "replica_delay":
                mon = _STRAGGLER
                if mon is not None:
                    mon.observe(int(mk.get("replica", 0)),
                                float(mk.get("seconds", 0.0)),
                                site="kvstore:allreduce")
        devs = self._mesh_devices()
        if len(datas) != len(devs) or len(devs) < 2:
            return None
        by_dev = {}
        for d in datas:
            dset = d.devices()
            if len(dset) != 1:
                return None
            by_dev.setdefault(next(iter(dset)), []).append(d)
        if set(by_dev) != set(devs) or any(len(v) != 1 for v in by_dev.values()):
            return None
        shape, dtype = datas[0].shape, datas[0].dtype
        mesh = self._mesh
        sharding = NamedSharding(
            mesh, P(tuple(mesh.axis_names), *([None] * len(shape))))
        # reshape-to-(1, ...) runs on each source device; the assembled
        # array is a view — no host or cross-device copies before the psum
        shards = [by_dev[dev][0].reshape((1,) + shape) for dev in devs]
        stacked = jax.make_array_from_single_device_arrays(
            (len(devs),) + shape, sharding, shards)
        summed = self._get_allreduce_jit(shape, dtype, stacked)(stacked)
        per_dev = {s.device: s.data for s in summed.addressable_shards}
        order = [next(iter(d.devices())) for d in datas]
        return [per_dev[dev] for dev in order]

    def allreduce(self, arrays):
        """Sum a list of per-device NDArrays into identical replicas.

        Per-device lists that cover the mesh run the compiled-collective
        path (`_collective_allreduce`): one jitted XLA all-reduce over ICI
        with a replicated out-sharding. Anything else (same-device lists,
        partial meshes) takes the eager stack-and-sum fallback.

        Resilience wrapping (outside → in): circuit breaker (skip the fast
        path entirely while open), retry with backoff (transient errors),
        watchdog (MXNET_COLLECTIVE_TIMEOUT bounds a hung collective — the
        watched body blocks on the result, so the timeout covers execution,
        not just dispatch). Any failure surfacing HERE records a
        degradation and falls through to the eager fallback instead of
        crashing. Scope caveat: with the watchdog disabled (the default)
        the result is returned async, so an execution-phase device failure
        surfaces later at a wait point (engine contract (c)) rather than
        through this retry/fallback — enable the watchdog to pull
        execution errors into the recovery path at the cost of a sync per
        reduce.
        """
        import jax
        import jax.numpy as jnp

        if len(arrays) == 1:
            return arrays
        datas = [a._data for a in arrays]
        if self._nan_quarantine:
            # BEFORE breaker/retry/watchdog: a poisoned input is not a
            # fast-path failure, and the eager fallback must not sum it
            # either
            dropped = self._quarantine_check(arrays, datas)
            if dropped is not None:
                return dropped
        t0 = _prof.begin() if _prof.ENABLED else 0
        self._stats["allreduce_calls"] += 1
        fast = None
        if self._breaker.allow():
            timeout = self._watchdog_timeout

            def run_fast():
                out = self._collective_allreduce(datas)
                if timeout and out is not None:
                    # under a watchdog the result must be BLOCKED on inside
                    # the watched body — async dispatch would return long
                    # before a hung ICI ring ever fails
                    for d in out:
                        d.block_until_ready()
                return out

            try:
                fast = _retry.call_with_retry(
                    lambda: _retry.run_with_watchdog(
                        run_fast, timeout, site="kvstore::allreduce"),
                    site="kvstore::allreduce",
                    policy=self._retry_policy)
            except Exception as exc:
                # never let the fast path take down a reduce the eager
                # fallback can do (odd meshes, unexpected layouts, injected
                # or real collective failures)
                fast = None
                self._breaker.record_failure()
                if self._elastic:
                    # mesh loss is NOT degradable: the eager fallback
                    # would keep summing a dead replica's stale buffer —
                    # silent divergence. Classify and raise so the
                    # elastic handler can shrink the mesh and resume.
                    mesh_err = self._classify_mesh_loss(exc)
                    if mesh_err is not None:
                        raise mesh_err from exc
                self._record_degradation(exc)
            except BaseException:
                # KeyboardInterrupt / SimulatedWorkerDeath mid-probe: the
                # half-open probe slot must not leak (a leaked slot locks
                # the store out of the collective path forever)
                self._breaker.release_probe()
                raise
            else:
                if fast is not None:
                    self._breaker.record_success()
                else:
                    # fast None without an exception: the list simply
                    # doesn't line up with the mesh — an expected shape of
                    # input, not a fast-path failure; the breaker stays
                    # put (but a half-open probe slot is released)
                    self._breaker.release_probe()
        else:
            self._stats["breaker_skips"] += 1
            if self._elastic:
                # the breaker never attempts the collective, so a chip
                # that dies DURING the cooldown throws no classifiable
                # error — probe the devices directly before letting the
                # eager fallback sum what might be a dead replica's
                # stale buffer
                lost = self._probe_lost_devices()
                if lost:
                    raise self._mesh_degraded(
                        lost, "device probe failed while the collective "
                        "breaker was open", "allreduce")
        if fast is not None:
            self._stats["collective"] += 1
            self.last_path = "collective"
            if t0:
                _prof.record_duration(
                    "kvstore::allreduce", "kvstore", t0,
                    args=_tag_step({
                        "path": "collective",
                        "shape": list(datas[0].shape),
                        "bytes": sum(int(d.nbytes) for d in datas)}))
            return [NDArray(d) for d in fast]
        self.last_path = "eager"
        self._stats["eager"] += 1
        # gather onto one device first: a per-device list degraded here by
        # a collective failure spans devices, and jnp.stack refuses mixed
        # placements (device_put is a no-op for the same-device case)
        dev0 = next(iter(datas[0].devices()))
        stacked = jnp.stack([jax.device_put(d, dev0) for d in datas])
        summed = jnp.sum(stacked, axis=0)
        out = []
        for a in arrays:
            dev = list(a._data.devices())[0]
            out.append(NDArray(jax.device_put(summed, dev)))
        if t0:
            _prof.record_duration(
                "kvstore::allreduce", "kvstore", t0,
                args=_tag_step({
                    "path": "eager", "shape": list(datas[0].shape),
                    "bytes": sum(int(d.nbytes) for d in datas)}))
        return out

    def _cross_process_sum(self, nd):
        """Sum one (already locally-reduced) array across processes —
        the multi-host half of pushpull (reference: ps-lite ZPushPull to
        servers shared by all workers; here a gather+sum over the
        jax.distributed runtime's collectives)."""
        import jax
        from jax.experimental import multihost_utils

        if _jax().process_count() <= 1:
            return nd
        gathered = multihost_utils.process_allgather(nd._data)
        dev = list(nd._data.devices())[0]
        return NDArray(jax.device_put(gathered.sum(axis=0), dev))

    def _maybe_compress(self, k, vals):
        """Per-replica 2-bit quantize (error-feedback residual keyed by
        ``(key, replica)``) BEFORE the reduce — the numerics of the
        reference's compressed ZPushPull, simulated over ICI. The dense
        quantized array still travels on-chip (packing it would only add
        an unpack gather); ``compressed_bytes_saved`` accounts what the
        ceil(n/4)-byte wire buffer WOULD save over DCN."""
        comp = self._compression
        if comp is None or len(vals) < 2:
            return vals
        import numpy as onp

        if not all(onp.issubdtype(onp.dtype(v.dtype), onp.floating)
                   for v in vals):
            return vals
        quantized = [comp.quantize((k, j), v) for j, v in enumerate(vals)]
        saved = sum(int(v.nbytes) - (int(v.size) + 3) // 4 for v in vals)
        self._stats["compressed_bytes_saved"] += max(saved, 0)
        return quantized

    def pushpull(self, key, value, out=None, priority=0):
        """Grouped push+pull over the mesh. ``priority`` follows the
        :func:`~.kvstore_local._priority_order` contract (scalar = call
        order; per-key list must be 1:1, higher settles first) and the
        settle order lands in ``_flush_log`` so overlap tests can assert
        front-layer grads beat the tail."""
        keys, values = _normalize_grouped(key, value)
        _, outs = _normalize_grouped(key, out)
        tpp = _prof.begin() if _prof.ENABLED else 0
        multi_proc = _jax().process_count() > 1
        for idx, prio in _priority_order(keys, priority):
            k, vals, dsts = keys[idx], values[idx], outs[idx]
            if vals is None or any(v is None for v in vals):
                # a None value group used to crash below (`reduced[0]` on
                # None, the TypeError satellite); a group with ANY None
                # entry is equally unusable — summing the remaining
                # entries would silently drop one replica's contribution.
                # Skip the key loudly instead.
                warnings.warn(
                    f"pushpull: key {k!r} has no usable value group "
                    f"({'None' if vals is None else 'contains None'}) — "
                    "skipping it; pass grads for every key or drop the "
                    "key from the call", RuntimeWarning, stacklevel=2)
                continue
            flt = _FAULTS
            if flt is not None:
                flt.check("kvstore:pushpull", {"key": k})
            vals = self._maybe_compress(k, vals)
            if len(vals) > 1:
                reduced = self.allreduce(vals)
            else:
                reduced = vals
            if multi_proc and reduced is not None:
                import jax

                summed = self._cross_process_sum(reduced[0])
                # keep each destination's device placement (the single-
                # process path preserves it too)
                reduced = [
                    NDArray(jax.device_put(
                        summed._data, list(r._data.devices())[0]))
                    for r in reduced]
            if dsts is None:
                self._store[k] = reduced[0]
                self._record_flush(k, prio)
                continue
            if len(reduced) == len(dsts):
                for r, d in zip(reduced, dsts):
                    d._set_data_internal(r._data)
            else:
                for d in dsts:
                    reduced[0].copyto(d)
            self._record_flush(k, prio)
        if tpp:
            _prof.record_duration(
                "kvstore::pushpull", "kvstore", tpp,
                args=_tag_step({
                    "keys": len(keys),
                    # None-tolerant like the skip-guard above: skipped
                    # keys/entries contribute 0 bytes, not a crash
                    "bytes": sum(v.nbytes for vs in values if vs
                                 for v in vs if v is not None)}))

    def broadcast(self, key, value, out, priority=0):
        """Replicate rank-0 value to all devices (reference Broadcast)."""
        keys, values = _normalize_grouped(key, value)
        _, outs = _normalize_grouped(key, out)
        import jax

        tbc = _prof.begin() if _prof.ENABLED else 0
        for k, vals, dsts in zip(keys, values, outs):
            src = vals[0]
            self._store[k] = src
            if dsts is None:
                continue

            def replicate(src=src, dsts=dsts):
                flt = _FAULTS
                if flt is not None:
                    flt.check("kvstore:broadcast", {"key": k})
                return [jax.device_put(src._data,
                                       list(d._data.devices())[0])
                        for d in dsts]

            # transfer faults (transient device_put failures) retry with
            # backoff; destinations are written only from a fully
            # successful replication pass
            placed = _retry.call_with_retry(
                replicate, site="kvstore::broadcast",
                policy=self._retry_policy)
            for d, buf in zip(dsts, placed):
                d._set_data_internal(buf)
        if tbc:
            _prof.record_duration("kvstore::broadcast", "kvstore", tbc,
                                  args=_tag_step({"keys": len(keys)}))

    # -- sharded-native helpers -------------------------------------------
    def shard(self, array: NDArray, spec):
        """Place an NDArray onto the mesh with a PartitionSpec."""
        import jax
        from jax.sharding import NamedSharding

        return NDArray(jax.device_put(array._data,
                                      NamedSharding(self._mesh, spec)))

    def reduce_scatter(self, array: NDArray, axis=0):
        """ZeRO-style sharded reduce (reference EncodeDefaultKey slicing)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = [None] * array.ndim
        spec[axis] = self._axis
        return NDArray(jax.jit(
            lambda x: x,
            out_shardings=NamedSharding(self._mesh, P(*spec)))(array._data))

    @staticmethod
    def is_capable(capability):
        # optimizer runs on workers (update_on_kvstore=False), like Horovod
        return False


# push/pull bandwidth probe used by bench.py and tools/bandwidth parity
def measure_pushpull_bandwidth(size_mb=64, iters=10, mesh=None):
    """Measured all-reduce bandwidth in GB/s per device (the role of the
    reference's ``tools/bandwidth/measure.py``).

    On a multi-device mesh this is collective bandwidth over ICI; on a
    single chip the "all-reduce" degenerates to an HBM read+write roundtrip
    of the buffer — callers should label the 1-device figure as
    ``hbm_roundtrip`` (see bench.py), not interconnect bandwidth.

    Timing takes the median of several two-loop differences and RAISES on
    degenerate or physically implausible results (>10 TB/s or <=0) instead
    of clamping — a wrong number is worse than no number.
    """
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel import mesh as mesh_mod

    mesh = mesh or mesh_mod.get_mesh(create=True)
    n = mesh.size
    nfloat = int(size_mb * 1024 * 1024 // 4)
    x = jax.device_put(
        jnp.ones((n, nfloat), jnp.float32),
        NamedSharding(mesh, P(mesh.axis_names[0], None)))
    import numpy as onp

    sharding = NamedSharding(mesh, P(mesh.axis_names[0], None))

    def allreduce(v):
        return jnp.broadcast_to(v.sum(0), v.shape) * 0.5

    # the reduce loop runs ON DEVICE (lax.scan): a host-side loop would
    # time per-dispatch runtime overhead (on the tunneled axon runtime a
    # per-execute RTT dwarfs the 64 MB reduce itself), not bandwidth
    import functools

    @functools.partial(jax.jit, static_argnums=1,
                       out_shardings=sharding)
    def run_n(v, m):
        def body(c, _):
            return allreduce(c), None
        out, _ = jax.lax.scan(body, v, None, length=m)
        return out

    x = run_n(x, 1)
    onp.asarray(jax.device_get(x[0, :1]))
    onp.asarray(jax.device_get(run_n(x, 1 + iters)[0, :1]))  # compile both

    # two-loop difference: some runtimes (the axon tunnel) return from
    # block_until_ready before execution finishes; an actual host fetch at
    # the end of BOTH loop lengths cancels that plus the fetch RTT
    def run(m, x):
        t0 = time.perf_counter()
        onp.asarray(jax.device_get(run_n(x, m)[0, :1]))
        return time.perf_counter() - t0
    diffs = []
    for _ in range(3):
        # baseline loop long enough that queue-ramp effects amortize the
        # same way in both runs (a 1-iteration baseline biases the
        # difference a few % fast — enough to read above HBM peak)
        k1 = max(2, iters // 8)
        d1 = run(k1, x)
        d2 = run(k1 + iters, x)
        if d2 > d1:
            diffs.append((d2 - d1) / iters)
    if not diffs:
        raise RuntimeError(
            "degenerate bandwidth timing: the longer loop never exceeded "
            "the shorter one — queue not drained, or the runtime elided "
            "the executions")
    diffs.sort()
    dt = diffs[len(diffs) // 2]
    if n > 1:
        # ring all-reduce moves 2*(n-1)/n of the data per device over ICI
        bytes_moved = 2 * (n - 1) / n * nfloat * 4
    else:
        # single chip: the reduce is one HBM read + write of the buffer —
        # report that roundtrip so the probe stays meaningful on 1 device
        bytes_moved = 2 * nfloat * 4
    gbs = bytes_moved / dt / 1e9  # GB/s per device
    if not (0.0 < gbs < 1e4):
        raise RuntimeError(
            f"implausible bandwidth {gbs:.1f} GB/s (dt={dt:.2e}s for "
            f"{bytes_moved/1e6:.0f} MB) — refusing to report it")
    return gbs
