"""Horovod KVStore backend (reference ``python/mxnet/kvstore/horovod.py``).

Kept for plugin-ABI parity: registers under 'horovod' and delegates to the
``horovod.mxnet`` package if present (it will not be on a TPU image); raises
with guidance otherwise — the TPU-native equivalent is ``dist_tpu_sync``.
"""
from __future__ import annotations

from ..base import MXNetError
from .base import KVStoreBase


@KVStoreBase.register
class Horovod(KVStoreBase):
    NAME = "horovod"

    def __init__(self):
        try:
            import horovod.mxnet as hvd  # noqa: F401
        except ImportError:
            raise MXNetError(
                "horovod is not available in this build; on TPU use "
                "kv.create('dist_tpu_sync') which provides the same "
                "allreduce data-parallel semantics over ICI") from None
