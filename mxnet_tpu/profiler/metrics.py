"""Step-level training telemetry: samples/s, tokens/s, MFU, device memory.

The role of the reference profiler's per-epoch summary rows, grown to the
numbers the BENCH trajectory actually tracks: ``TrainingMetrics`` turns
step wall-times plus a FLOP estimate into an MFU figure against the local
chip's peak (the accounting ``bench.py`` headline rows use), and
``device_memory_stats`` surfaces ``jax.local_devices()[i].memory_stats()``
per device.  ``profiler.step_marker()`` marks step boundaries on a default
``TrainingMetrics`` and emits a ``train::step`` trace range while the
profiler runs.
"""
from __future__ import annotations

import collections
import os
import statistics
import time

from . import core

# per-chip peaks by jax device_kind prefix:
# (bf16 MXU flops/s, HBM bytes/s, ICI GB/s per link-direction pair).
# Longest-prefix entries first where prefixes overlap ("TPU v5 lite"
# before "TPU v5") — chip_peak matches in declaration order.
CHIP_PEAKS = {
    "TPU v4": (275e12, 1228e9, 100e9),
    "TPU v5 lite": (197e12, 819e9, 100e9),
    "TPU v5p": (459e12, 2765e9, 200e9),
    "TPU v5e": (197e12, 819e9, 100e9),
    "TPU v5": (459e12, 2765e9, 200e9),
    "TPU v6 lite": (918e12, 1640e9, 200e9),
    "TPU v6e": (918e12, 1640e9, 200e9),
}


def chip_peak(what):
    """Peak for the local chip: what = 'flops' | 'hbm' | 'ici'.
    None when the device kind is unknown (e.g. CPU test runs)."""
    import jax

    kind = jax.devices()[0].device_kind
    for k, v in CHIP_PEAKS.items():
        if kind.startswith(k):
            return v[{"flops": 0, "hbm": 1, "ici": 2}[what]]
    return None


def peak_flops():
    """MFU denominator: MXNET_TPU_PEAK_FLOPS override, else by device_kind."""
    env = os.environ.get("MXNET_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    return chip_peak("flops")


def process_peak_bytes_in_use():
    """Max allocator peak over the local devices — since PROCESS start
    (jax never resets it), so an upper bound on the current workload's
    footprint. 0 on backends that don't report (CPU)."""
    return max((m.get("peak_bytes_in_use", 0)
                for m in device_memory_stats()), default=0)


def device_memory_stats(device_index=None):
    """Per-device ``memory_stats()`` dicts (``bytes_in_use``,
    ``peak_bytes_in_use``, ... on TPU; ``{}`` on backends that don't
    report, e.g. CPU). One dict per ``jax.local_devices()`` entry, each
    tagged with its device string."""
    import jax

    out = []
    for d in jax.local_devices():
        try:
            ms = d.memory_stats() or {}
        except Exception:
            ms = {}
        out.append({"device": str(d), **ms})
    if device_index is not None:
        return out[device_index]
    return out


class TrainingMetrics:
    """Aggregates per-step wall times into throughput and MFU.

    ``flops_per_step`` is the FLOP estimate of one training step (e.g.
    XLA ``cost_analysis()['flops']`` of the compiled step — what
    ``bench.py`` feeds in); ``samples_per_step`` / ``tokens_per_step``
    are the per-step batch sizes.  Rates use the MEDIAN step time (robust
    to tunnel-weather outliers, matching bench.py's two-loop-difference
    methodology); totals are kept too for long-run accounting.
    """

    def __init__(self, flops_per_step=None, samples_per_step=None,
                 tokens_per_step=None, peak_flops=None, window=1024):
        self.flops_per_step = flops_per_step
        self.samples_per_step = samples_per_step
        self.tokens_per_step = tokens_per_step
        self.peak_flops = peak_flops
        self.steps = 0
        self.total_time_s = 0.0
        self.total_samples = 0
        self.total_tokens = 0
        self.total_flops = 0.0
        self._durations = collections.deque(maxlen=window)
        self._t_last_ns = None

    # -- recording --------------------------------------------------------
    def record_step(self, duration_s, samples=None, tokens=None, flops=None):
        """Record one completed step of ``duration_s`` seconds."""
        self.steps += 1
        self.total_time_s += duration_s
        self._durations.append(duration_s)
        s = samples if samples is not None else self.samples_per_step
        if s:
            self.total_samples += s
        t = tokens if tokens is not None else self.tokens_per_step
        if t:
            self.total_tokens += t
        f = flops if flops is not None else self.flops_per_step
        if f:
            self.total_flops += f

    def step_marker(self, samples=None, tokens=None, flops=None):
        """Mark a step boundary; the first call starts the clock, each
        subsequent call records the inter-marker duration. Returns the
        step duration in seconds (None on the first call)."""
        now = time.perf_counter_ns()
        t_last, self._t_last_ns = self._t_last_ns, now
        if t_last is None:
            return None
        self.record_step((now - t_last) / 1e9, samples, tokens, flops)
        if core.ENABLED:
            core.record_duration("train::step", "metrics", t_last, now,
                                 args={"step": self.steps})
        return (now - t_last) / 1e9

    def reset(self):
        self.steps = 0
        self.total_time_s = 0.0
        self.total_samples = 0
        self.total_tokens = 0
        self.total_flops = 0.0
        self._durations.clear()
        self._t_last_ns = None

    # -- derived numbers --------------------------------------------------
    @property
    def median_step_s(self):
        if not self._durations:
            return None
        return statistics.median(self._durations)

    def _rate(self, per_step, total):
        dt = self.median_step_s
        if per_step and dt:
            return per_step / dt
        if total and self.total_time_s > 0:
            return total / self.total_time_s
        return None

    @property
    def samples_per_sec(self):
        return self._rate(self.samples_per_step, self.total_samples)

    @property
    def tokens_per_sec(self):
        return self._rate(self.tokens_per_step, self.total_tokens)

    @property
    def mfu(self):
        """Model FLOP utilization: flops_per_step / (median step time *
        chip peak). None without a FLOP estimate or a known peak."""
        peak = self.peak_flops or peak_flops()
        dt = self.median_step_s
        f = self.flops_per_step
        if not f and self.steps:
            f = self.total_flops / self.steps
        if not (peak and dt and f):
            return None
        return f / (dt * peak)

    def memory(self):
        return device_memory_stats()

    def summary(self):
        """One JSON-able dict with every derived figure (what bench rows
        consume)."""
        dt = self.median_step_s
        peak_mem = process_peak_bytes_in_use()
        return {
            "steps": self.steps,
            "median_step_ms": round(dt * 1e3, 4) if dt else None,
            "samples_per_sec": self.samples_per_sec,
            "tokens_per_sec": self.tokens_per_sec,
            "mfu": self.mfu,
            "peak_flops": self.peak_flops or peak_flops(),
            "process_peak_bytes_in_use": peak_mem or None,
        }


_default_metrics = TrainingMetrics()


def training_metrics() -> TrainingMetrics:
    """The process-default TrainingMetrics fed by ``step_marker()``."""
    return _default_metrics


def step_marker(samples=None, tokens=None, flops=None, metrics=None):
    """Mark a training-step boundary (module-level convenience over
    :class:`TrainingMetrics`). Returns the step duration in seconds, or
    None on the first call."""
    return (metrics or _default_metrics).step_marker(
        samples=samples, tokens=tokens, flops=flops)
