"""Decode critical-path attribution: WHERE each decode iteration's wall
time goes.

ROADMAP item 3 ("kill the host in the decode loop") names its acceptance
metric — "``engine:wait`` near zero in steady-state decode, ITL p50
within ~1.5x of pure kernel time" — and this module is the instrument
that produces it. Every decode iteration's wall time is split into four
exclusive phases:

* **host**     — python bookkeeping inside the step (sampling dict
  assembly, token accounting) plus, at the scheduler level, the
  admit/retire work between device calls (the *schedule* bucket);
* **dispatch** — issuing the step executable (async: the call returns
  before the device finishes);
* **device**   — the delta around the blocking fetch of the step's
  logits (argmax/sample + device->host copy). Cross-checkable against
  ``profiler.xla.device_op_stats`` when an XLA capture is live
  (:func:`device_cross_check`);
* **wait**     — ``engine:wait`` stalls *outside* the sanctioned
  blocking fetch, fed by the (now phase-tagged) wait hooks in
  ``engine.py``.

The four phases partition the ``serve::decode_step`` span wall exactly
(``tools/trace_check.py --expect-attribution`` asserts the sum lands
within 10%), roll up into per-engine :class:`Ledger` gauges
(``host_overhead_fraction``, ``device_ms_per_token`` — published through
``ServeMetrics`` so they ride ``export.snapshot()`` as
``serve.<name>.*``), and compose into per-request critical-path reports
keyed by PR-9 trace ids (:func:`report`).

Hot-path contract (the PR-1/PR-9 rule): everything is gated on the
module-level ``ENABLED`` bool (``MXNET_ATTRIBUTION=1`` or
:func:`enable`); a disabled ledger costs one attribute load and a branch
per site, and the ``engine.py`` wait hooks see this module through the
same ``_ATTR`` slot pattern as ``_PROF`` — ``None`` until the profiler
package imports, one ``is None`` test when absent.

Phase *scopes* (:func:`phase_scope`) are independent of ``ENABLED``:
the scheduler/generator/estimator always label their thread's active
phase (an attribute store), so ``engine::wait_*`` profiler events carry
a ``phase`` arg whenever the bus records, attribution on or off.
"""
from __future__ import annotations

import collections
import threading
import weakref

from . import trace as _trace

ENABLED = False

_tls = threading.local()
_lock = threading.Lock()
# process-wide engine:wait stall totals by phase (ns) — the "engine:wait
# near zero in steady-state decode" query is a read of this dict
_wait_ns_by_phase: "collections.Counter" = collections.Counter()
# live Ledgers, for export.snapshot() pull-discovery (weak: a retired
# engine's ledger is simply no longer exported)
_instances: "weakref.WeakSet" = weakref.WeakSet()

PHASES = ("decode", "prefill", "train", "input", "other")


def enable():
    """Turn the ledger on and point ``engine._ATTR`` at this module (the
    wait hooks feed :func:`note_wait` through that slot)."""
    global ENABLED
    _install_engine_slot()
    ENABLED = True


def disable():
    global ENABLED
    ENABLED = False


def _install_engine_slot():
    import sys

    from .. import engine as _engine

    _engine._ATTR = sys.modules[__name__]


def reset():
    """Drop accumulated wait totals (tests)."""
    with _lock:
        _wait_ns_by_phase.clear()
    _tls.wait_ns = 0


# -- phase scopes ------------------------------------------------------------

class _PhaseCtx:
    __slots__ = ("_phase", "_prev")

    def __init__(self, phase):
        self._phase = phase

    def __enter__(self):
        self._prev = getattr(_tls, "phase", None)
        _tls.phase = self._phase
        return self

    def __exit__(self, *a):
        _tls.phase = self._prev
        return False


def phase_scope(phase):
    """Label the calling thread's active phase (``decode`` / ``prefill``
    / ``train`` / ``input`` / ``other``) for the ``with`` body. Engine
    wait stalls inside the scope are tagged with it."""
    return _PhaseCtx(phase)


def current_phase():
    """The calling thread's active phase ("other" when unlabeled)."""
    return getattr(_tls, "phase", None) or "other"


# -- wait capture (fed by engine.py's wait hooks) ----------------------------

def note_wait(dur_ns, phase=None):
    """Account one ``engine:wait`` stall of ``dur_ns`` against the
    calling thread's running total and the per-phase process totals.
    Called from ``engine.wait_for_var`` / ``engine.wait_all`` while
    ``ENABLED``."""
    if not ENABLED:
        return
    dur_ns = int(dur_ns)
    _tls.wait_ns = getattr(_tls, "wait_ns", 0) + dur_ns
    p = phase or current_phase()
    with _lock:
        _wait_ns_by_phase[p] += dur_ns


def thread_wait_ns():
    """The calling thread's monotonically-increasing accumulated wait ns
    (never reset): instrumented loops snapshot it at window boundaries
    and difference the snapshots."""
    return getattr(_tls, "wait_ns", 0)


def wait_ms_by_phase():
    """``{phase: total_ms}`` of engine:wait stall time since import (or
    :func:`reset`). ``wait_ms_by_phase().get("decode", 0.0)`` is ROADMAP
    item 3's acceptance query."""
    with _lock:
        return {k: v / 1e6 for k, v in _wait_ns_by_phase.items()}


# -- the per-engine ledger ---------------------------------------------------

class Ledger:
    """Rolling per-iteration phase ledger for one engine/generator.

    :meth:`observe_step` lands one decode iteration's four-way split
    (partitioning the ``serve::decode_step`` span wall) plus the live
    slot count; :meth:`observe_schedule` lands the host-schedule time
    *between* device calls (retire/admit bookkeeping, input-array
    assembly). Bounded window so a long-lived server's gauges track
    steady state, not its cold start.
    """

    __slots__ = ("name", "_lock", "_rows", "_sched_ms", "steps",
                 "__weakref__")

    def __init__(self, name, window=None):
        if window is None:
            from .. import config

            window = config.get("MXNET_ATTRIBUTION_WINDOW")
        self.name = name
        self._lock = threading.Lock()
        # (host_ms, dispatch_ms, device_ms, wait_ms, live, tokens)
        self._rows = collections.deque(maxlen=int(window))
        self._sched_ms = collections.deque(maxlen=int(window))
        self.steps = 0
        _instances.add(self)

    def observe_step(self, host_ms, dispatch_ms, device_ms, wait_ms,
                     live=1, tokens=None):
        """One decode host visit's exclusive four-phase split (ms), its
        live-slot count, and the tokens it produced. In the classic
        single-step loop one visit is one iteration and ``tokens`` can
        stay ``None`` (it defaults to ``live``: every live slot emits
        one token). A multi-step super-step passes ``tokens`` explicitly
        — host/dispatch/wait are real per-visit costs (paid once for the
        whole block), while device time covers N iterations, so
        ``device_ms_per_token`` must divide by tokens, not visits."""
        with self._lock:
            self._rows.append((float(host_ms), float(dispatch_ms),
                               float(device_ms), float(wait_ms),
                               int(live),
                               int(live if tokens is None else tokens)))
            self.steps += 1

    def observe_schedule(self, ms):
        """Host-schedule time between device calls (retire/admit, input
        assembly) for one scheduler iteration."""
        with self._lock:
            self._sched_ms.append(float(ms))

    def _totals(self):
        host = dispatch = device = wait = 0.0
        tokens = 0
        for h, di, de, w, live, tok in self._rows:
            host += h
            dispatch += di
            device += de
            wait += w
            tokens += tok
        return host, dispatch, device, wait, tokens, sum(self._sched_ms)

    def host_overhead_fraction(self):
        """Fraction of windowed iteration wall NOT spent in the blocking
        device window: (schedule + host + dispatch + wait) / total.
        0.0 with no samples; in [0, 1] by construction."""
        with self._lock:
            host, dispatch, device, wait, _, sched = self._totals()
        total = sched + host + dispatch + device + wait
        if total <= 0.0:
            return 0.0
        return (sched + host + dispatch + wait) / total

    def device_ms_per_token(self):
        """Windowed device-compute ms per emitted token (device phase
        normalized by live-slot occupancy — the number ITL p50 is judged
        against)."""
        with self._lock:
            _, _, device, _, tokens, _ = self._totals()
        return device / tokens if tokens else 0.0

    def snapshot(self):
        with self._lock:
            host, dispatch, device, wait, tokens, sched = self._totals()
            n = len(self._rows)
            steps = self.steps
        total = sched + host + dispatch + device + wait
        return {
            "steps": steps,
            "window": n,
            "host_ms": round(host, 3),
            "dispatch_ms": round(dispatch, 3),
            "device_ms": round(device, 3),
            "wait_ms": round(wait, 3),
            "schedule_ms": round(sched, 3),
            "tokens": tokens,
            "tokens_per_visit": tokens / n if n else 0.0,
            "host_overhead_fraction": (
                (sched + host + dispatch + wait) / total if total else 0.0),
            "device_ms_per_token": device / tokens if tokens else 0.0,
        }


def all_snapshots():
    """``{ledger_name: snapshot()}`` over every live ledger (same-named
    ledgers merge last-writer-wins, like ``serve.metrics``)."""
    return {l.name: l.snapshot() for l in list(_instances)}


# -- per-request critical path -----------------------------------------------

_LEDGER_KEYS = ("host_ms", "dispatch_ms", "device_ms", "wait_ms")


def _bucket(name):
    if "queue" in name:
        return "queue"
    if "prefill" in name:
        return "prefill"
    if "decode" in name:
        return "decode"
    if "settle" in name or "execute" in name or "session_run" in name:
        return "settle"
    return "other"


def report(trace_id):
    """Per-request critical-path attribution for one PR-9 trace id:
    queue -> prefill chunks -> N decode super-steps -> settle, with the
    decode super-steps' four-phase ledger totals summed from the
    ``serve::decode_step`` span args. ``None`` if the trace is unknown
    or evicted."""
    s = _trace.summary(trace_id)
    if s is None:
        return None
    phase_ms = {"queue": 0.0, "prefill": 0.0, "decode": 0.0,
                "settle": 0.0, "other": 0.0}
    counts = {"prefill": 0, "decode": 0}
    ledger = dict.fromkeys(_LEDGER_KEYS, 0.0)
    ledger_steps = 0
    ledger_tokens = 0
    for span in s["spans"]:
        b = _bucket(span["name"])
        phase_ms[b] += span["dur_ms"]
        if b in counts:
            counts[b] += 1
        args = span.get("args")
        if span["name"] == "serve::decode_step" and args \
                and all(k in args for k in _LEDGER_KEYS):
            ledger_steps += 1
            # multi-step visits stamp the tokens their block settled;
            # classic single-step spans predate the arg and count 1
            ledger_tokens += int(args.get("tokens", 1))
            for k in _LEDGER_KEYS:
                ledger[k] += float(args[k])
    accounted = sum(phase_ms.values())
    total = s["total_ms"]
    return {
        "trace_id": s["trace_id"],
        "name": s["name"],
        "finished": s["finished"],
        "error": s["error"],
        "total_ms": total,
        "queue_ms": phase_ms["queue"],
        "prefill_ms": phase_ms["prefill"],
        "prefill_chunks": counts["prefill"],
        "decode_ms": phase_ms["decode"],
        "decode_steps": counts["decode"],
        "settle_ms": phase_ms["settle"],
        "other_ms": phase_ms["other"],
        "phase_ledger": {k: round(v, 3) for k, v in ledger.items()},
        "ledger_steps": ledger_steps,
        "ledger_tokens": ledger_tokens,
        "tokens_per_visit": (ledger_tokens / ledger_steps
                             if ledger_steps else 0.0),
        "coverage": accounted / total if total > 0 else 0.0,
    }


def device_cross_check(ledger_device_ms, trace_dir):
    """Cross-check the ledger's blocking-fetch device estimate against
    an XLA capture's per-op device rows (``xla.device_op_stats``).
    Returns ``{"ledger_device_ms", "xla_device_ms", "ratio"}``, or
    ``None`` when the capture has no device rows (pure-CPU run) or can't
    be parsed — the ledger stands alone there."""
    from ..base import MXNetError
    from . import xla as _xla

    try:
        rows = _xla.device_op_stats(trace_dir)
    except (MXNetError, OSError, ValueError):
        return None
    xla_ms = sum(float(r.get("total_us", 0.0)) for r in rows) / 1e3
    if xla_ms <= 0.0:
        return None
    led = float(ledger_device_ms)
    return {"ledger_device_ms": led, "xla_device_ms": xla_ms,
            "ratio": led / xla_ms}
