"""In-process telemetry event bus: scoped ranges, counters, chrome trace.

Reference: ``src/profiler/profiler.cc`` (the chrome://tracing JSON writer
behind ``MXDumpProfile``) and ``src/profiler/aggregate_stats.cc`` (the
``dumps(reset)`` tables).  This module is the host-side store both map onto:
instrumented call sites append complete ('X') events and counter ('C')
events here, and every duration also lands in an aggregate
``name -> [calls, total_s]`` table.

Hot-path contract (the reason this module exists separately from the
facade): instrumented modules guard each hook on the module-level
``ENABLED`` / ``IMPERATIVE`` bools below — one attribute load and a branch
when the profiler is stopped, no dict lookups, no function calls.  The
hottest site of all (``ops/registry.apply``) goes one step further and
checks an installed-module slot (``registry._PROF``) that stays ``None``
until the first ``set_state('run')``, so sessions that never profile pay a
single ``is None`` test per dispatch.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

# -- hot flags (read by instrumented modules; written by the facade) --------
ENABLED = False      # event bus recording is on (profiler.set_state('run'))
IMPERATIVE = False   # per-op dispatch counters (set_config(profile_imperative=True))

_MAX_EVENTS = 2_000_000  # hard cap; beyond it events are counted as dropped

_lock = threading.Lock()             # guards events, aggregates and counters
_events: list = []                   # chrome trace event dicts
_dropped = 0
_epoch_ns = time.perf_counter_ns()   # ts origin for the whole process
_agg = collections.defaultdict(lambda: [0, 0.0])  # name -> [calls, total_s]
_op_counts: collections.Counter = collections.Counter()  # imperative op calls
_counters: dict = {}                 # counter name -> last value
_thread_names: dict = {}             # tid -> human name ('M' metadata events)


def begin() -> int:
    """Timestamp for a range about to be recorded (perf_counter_ns)."""
    return time.perf_counter_ns()


def _ts_us(ns: int) -> float:
    return (ns - _epoch_ns) / 1e3


def start():
    global ENABLED
    ENABLED = True


def stop():
    global ENABLED, IMPERATIVE
    ENABLED = False
    IMPERATIVE = False


def is_running() -> bool:
    return ENABLED


def reset():
    """Drop all recorded events, aggregates and counters."""
    global _dropped
    with _lock:
        _events.clear()
        _agg.clear()
        _op_counts.clear()
        _counters.clear()
        _dropped = 0


def register_thread_name(name=None, tid=None):
    """Name the calling thread (or ``tid``) in dumped traces via a chrome
    'M' ``thread_name`` metadata event. Long-lived worker threads (batcher
    flusher, prefetch worker) call this once at startup; registration is
    kept across ``reset()`` so a later dump still labels them."""
    if tid is None:
        tid = threading.get_ident() & 0xFFFFFFFF
    if name is None:
        name = threading.current_thread().name
    with _lock:
        _thread_names[int(tid)] = str(name)


def append_event(ev):
    """Append a pre-built chrome event dict (trace/flow emitters). Honors
    the same ``ENABLED`` gate and event cap as the record_* helpers."""
    global _dropped
    if not ENABLED:
        return False
    with _lock:
        if len(_events) >= _MAX_EVENTS:
            _dropped += 1
            return False
        _events.append(ev)
    return True


def record_duration(name, cat, t0_ns, t1_ns=None, args=None):
    """One completed range: aggregates always, a chrome 'X' event when the
    bus is running (so ``profiler.scope`` keeps feeding ``dumps()`` even
    with the profiler stopped — the pre-package behavior)."""
    global _dropped
    if t1_ns is None:
        t1_ns = time.perf_counter_ns()
    dur_s = (t1_ns - t0_ns) / 1e9
    enabled = ENABLED
    with _lock:
        row = _agg[name]
        row[0] += 1
        row[1] += dur_s
        if not enabled:
            return
        if len(_events) >= _MAX_EVENTS:
            _dropped += 1
            return
        ev = {"ph": "X", "name": name, "cat": cat or "host",
              "pid": os.getpid(), "tid": threading.get_ident() & 0xFFFFFFFF,
              "ts": round(_ts_us(t0_ns), 3),
              "dur": round((t1_ns - t0_ns) / 1e3, 3)}
        if args:
            ev["args"] = args
        _events.append(ev)


def record_instant(name, cat="host", args=None):
    """A point-in-time marker (chrome 'i' event)."""
    global _dropped
    if not ENABLED:
        return
    ev = {"ph": "i", "s": "t", "name": name, "cat": cat,
          "pid": os.getpid(), "tid": threading.get_ident() & 0xFFFFFFFF,
          "ts": round(_ts_us(time.perf_counter_ns()), 3)}
    if args:
        ev["args"] = args
    with _lock:
        if len(_events) >= _MAX_EVENTS:
            _dropped += 1
            return
        _events.append(ev)


def _counter_event(name, value, cat):
    """Append the chrome 'C' gauge event. Caller holds ``_lock``."""
    global _dropped
    if len(_events) >= _MAX_EVENTS:
        _dropped += 1
        return
    _events.append({"ph": "C", "name": name, "cat": cat,
                    "pid": os.getpid(),
                    "ts": round(_ts_us(time.perf_counter_ns()), 3),
                    "args": {"value": value}})


def set_counter(name, value, cat="counters"):
    """Record a gauge value (chrome 'C' event when running)."""
    with _lock:
        _counters[name] = value
        if ENABLED:
            _counter_event(name, value, cat)


def incr_counter(name, delta=1, cat="counters"):
    """Atomic counter bump: the read-modify-write happens under ``_lock``
    so concurrent increments from batcher/flusher/engine threads never
    lose counts."""
    with _lock:
        value = _counters.get(name, 0) + delta
        _counters[name] = value
        if ENABLED:
            _counter_event(name, value, cat)
    return value


def get_counter(name, default=0):
    return _counters.get(name, default)


def counters_snapshot():
    """Consistent copy of every counter gauge."""
    with _lock:
        return dict(_counters)


def count_op(name):
    """Imperative dispatch counter (guarded by IMPERATIVE at the call
    site). A bare Counter increment — no event, no lock: losing a rare
    racy increment is acceptable for call statistics."""
    _op_counts[name] += 1


def op_counts():
    return dict(_op_counts)


def aggregate_stats():
    """``{name: {"calls", "total_s", "avg_s"}}`` over all recorded ranges."""
    with _lock:
        return {
            name: {"calls": cnt, "total_s": tot,
                   "avg_s": tot / cnt if cnt else 0.0}
            for name, (cnt, tot) in _agg.items()
        }


def dumps_table(reset_after=False):
    """Formatted aggregate table (``MXAggregateProfileStatsPrint`` analog):
    ranges by total time, then per-op imperative call counts, then the
    latest counter gauges."""
    lines = [f"{'Name':<44}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
    with _lock:
        rows = sorted(_agg.items(), key=lambda kv: -kv[1][1])
    for name, (cnt, total) in rows:
        lines.append(f"{name:<44}{cnt:>8}{total * 1e3:>12.3f}"
                     f"{total / max(cnt, 1) * 1e3:>12.3f}")
    if _op_counts:
        lines.append("")
        lines.append(f"{'Operator (imperative)':<44}{'Calls':>8}")
        for name, cnt in _op_counts.most_common():
            lines.append(f"{name:<44}{cnt:>8}")
    if _counters:
        lines.append("")
        lines.append(f"{'Counter':<44}{'Value':>12}")
        for name in sorted(_counters):
            lines.append(f"{name:<44}{_counters[name]:>12}")
    if reset_after:
        # aggregate STATS only (the reference dumps(reset) contract):
        # the chrome-trace events and counter gauges survive for dump()
        with _lock:
            _agg.clear()
            _op_counts.clear()
    return "\n".join(lines)


def snapshot_events():
    """Copy of the recorded chrome events (tests / tooling)."""
    with _lock:
        return list(_events)


def _meta_events():
    """Chrome 'M' metadata: process name plus a ``thread_name`` row for
    every registered worker thread and every currently-live thread, so
    Perfetto lanes read "mxtpu-serve-batcher[x]" instead of bare tids."""
    pid = os.getpid()
    names = dict(_thread_names)
    for t in threading.enumerate():
        if t.ident is not None:
            names.setdefault(t.ident & 0xFFFFFFFF, t.name)
    meta = [{"ph": "M", "pid": pid, "name": "process_name",
             "args": {"name": "mxnet_tpu host"}}]
    for tid in sorted(names):
        meta.append({"ph": "M", "pid": pid, "tid": tid,
                     "name": "thread_name", "args": {"name": names[tid]}})
    return meta


def dump(path):
    """Write the chrome://tracing JSON (reference ``dump()`` contract:
    load the file in chrome://tracing or Perfetto). Returns ``path``.
    The event-list copy happens under ``_lock`` so a dump racing live
    appends can't serialize a half-written list."""
    with _lock:
        events = list(_events)
        dropped = _dropped
    doc = {"traceEvents": _meta_events() + events, "displayTimeUnit": "ms"}
    if dropped:
        doc["mxnet_tpu_dropped_events"] = dropped
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
