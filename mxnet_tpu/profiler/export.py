"""Unified telemetry export: every subsystem's counters behind ONE
``snapshot()`` — profiler aggregates/counters, ``engine`` dispatch/bulk
stats, ``cachedop.cache_stats()``, ``kvstore.dist_tpu
.collective_stats()``, the ``resilience.*`` counters, per-instance
``ServeMetrics`` percentiles/goodput, per-replica straggler gauges, and
the flight-recorder/trace bookkeeping — flattened into a single
namespaced dict (``serve.<name>.p99_ms``, ``kvstore.breaker_state``,
``resilience.retries``...).

The same snapshot renders as Prometheus text exposition
(:func:`render_prometheus`) and can be served over stdlib HTTP
(:func:`start_http` / ``MXNET_METRICS_PORT``):

* ``GET /metrics``  — Prometheus text format
* ``GET /healthz``  — JSON wrapping every registered serving session's
  ``health()``/``ready()`` probes; 200 when all ready, else 503
* ``GET /snapshot`` — the full snapshot as JSON

Aggregation is *pull-based*: providers are discovered through
``sys.modules`` so a training-only process never imports the serving
stack (and vice versa), and instance registries are weak sets so the
exporter never pins a retired server or store.
"""
from __future__ import annotations

import json
import sys
import threading
import weakref

from .. import config as _cfg
from . import core as _core
from . import recorder as _recorder
from . import trace as _trace

# serving sessions answering /healthz (weak: a collected session is
# simply no longer probed). InferenceSession registers itself.
_health_providers: "weakref.WeakSet" = weakref.WeakSet()

_server = None
_server_thread = None
_server_lock = threading.Lock()


def register_health_provider(obj):
    """Register an object with ``health()``/``ready()`` (the serving
    session contract) for the ``/healthz`` endpoint."""
    _health_providers.add(obj)


def unregister_health_provider(obj):
    """Remove ``obj`` from the ``/healthz`` roll. The fleet Router calls
    this for each replica-owned session it adopts: the Router itself is
    the fleet's single health provider, so one dead (and routed-around)
    replica doesn't wedge the whole process's /healthz at 503."""
    _health_providers.discard(obj)


def _flatten(prefix, value, out):
    if isinstance(value, dict):
        for k, v in value.items():
            if isinstance(k, (int, float)):
                _flatten(f"{prefix}[{k}]", v, out)
            else:
                _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    elif isinstance(value, (list, tuple)):
        out[prefix] = json.dumps(value)
    else:
        out[prefix] = value


def snapshot(include_aggregates=True):
    """One flat ``{namespaced_name: value}`` dict over every subsystem
    currently alive in the process. Never imports a subsystem the
    process hasn't touched (``sys.modules`` discovery)."""
    out = {}

    # profiler bus: counter gauges are already namespaced at the source
    # (resilience.* / serve.* / cachedop.* / engine.* / registry.*)
    for k, v in _core.counters_snapshot().items():
        out[k] = v
    if include_aggregates:
        for name, row in _core.aggregate_stats().items():
            out[f"profiler.agg.{name}.calls"] = row["calls"]
            out[f"profiler.agg.{name}.total_s"] = row["total_s"]
    out["profiler.dropped_events"] = _core._dropped
    out["profiler.recording"] = int(_core.ENABLED)

    eng = sys.modules.get("mxnet_tpu.engine")
    if eng is not None:
        out["engine.dispatches"] = eng.dispatch_count()
        _flatten("engine.bulk", eng.bulk_stats(), out)

    cop = sys.modules.get("mxnet_tpu.cachedop")
    if cop is not None:
        _flatten("cachedop", cop.cache_stats(), out)

    cc = sys.modules.get("mxnet_tpu.compile_cache")
    if cc is not None:
        _flatten("compile_cache", cc.stats(), out)

    tenancy = sys.modules.get("mxnet_tpu.serve.tenancy")
    if tenancy is not None:
        for name, snap in tenancy.registry_stats().items():
            _flatten(f"tenancy.{name}", snap, out)

    kv = sys.modules.get("mxnet_tpu.kvstore.dist_tpu")
    if kv is not None:
        _flatten("kvstore", kv.collective_stats(), out)

    bk = sys.modules.get("mxnet_tpu.kvstore.bucketing")
    if bk is not None:
        _flatten("kvstore", bk.bucket_stats(), out)

    rescnt = sys.modules.get("mxnet_tpu.resilience.counters")
    if rescnt is not None:
        for k, v in rescnt.snapshot().items():
            out[k] = v  # names carry the resilience. prefix already

    elastic = sys.modules.get("mxnet_tpu.resilience.elastic")
    if elastic is not None and elastic._active_monitor is not None:
        _flatten("resilience.straggler",
                 elastic._active_monitor.snapshot(), out)

    retry = sys.modules.get("mxnet_tpu.resilience.retry")
    if retry is not None:
        for name, bstate in retry.breaker_states().items():
            _flatten(f"resilience.breaker.{name}", bstate, out)

    smet = sys.modules.get("mxnet_tpu.serve.metrics")
    if smet is not None:
        for name, snap in smet.all_snapshots().items():
            snap.pop("name", None)
            _flatten(f"serve.{name}", snap, out)

    fleet = sys.modules.get("mxnet_tpu.serve.fleet")
    if fleet is not None:
        for name, snap in fleet.fleet_stats().items():
            _flatten(f"fleet.{name}", snap, out)

    slo_mod = sys.modules.get("mxnet_tpu.profiler.slo")
    if slo_mod is not None:
        for name, snap in slo_mod.all_snapshots().items():
            _flatten(f"slo.{name}", snap, out)

    # input pipeline: io.<name>.* gauges from live RecordPipelines /
    # DeviceFeeders (queue depth, worker utilization, bytes/s, stall ms)
    # and PrefetchIter prefetch_stats()
    iomod = sys.modules.get("mxnet_tpu.io.pipeline")
    if iomod is not None:
        for name, snap in iomod.io_stats().items():
            _flatten(f"io.{name}", snap, out)
    io_pkg = sys.modules.get("mxnet_tpu.io")
    if io_pkg is not None:
        for name, snap in io_pkg.prefetch_stats_all().items():
            _flatten(f"io.{name}", snap, out)

    attr_mod = sys.modules.get("mxnet_tpu.profiler.attribution")
    if attr_mod is not None:
        for name, snap in attr_mod.all_snapshots().items():
            _flatten(f"attribution.{name}", snap, out)
        for phase, ms in attr_mod.wait_ms_by_phase().items():
            out[f"attribution.wait_ms[{phase}]"] = round(ms, 3)

    out["recorder.enabled"] = int(_recorder.ENABLED)
    out["recorder.notes"] = _recorder._seq
    out["recorder.dumps"] = _recorder.dump_count()
    out["trace.enabled"] = int(_trace.ENABLED)
    with _trace._lock:
        out["trace.registered"] = len(_trace._registry)
    return out


# -- Prometheus text rendering ----------------------------------------------

def _prom_name(key):
    """``serve.smoke.p99_ms`` -> ``mxnet_serve_smoke_p99_ms``; a trailing
    ``[idx]`` subscript becomes a ``key`` label."""
    label = None
    if key.endswith("]") and "[" in key:
        key, _, sub = key.rpartition("[")
        label = sub[:-1]
    name = "mxnet_" + "".join(
        c if c.isalnum() or c == "_" else "_" for c in key)
    return name, label


def render_prometheus(snap=None):
    """Prometheus text exposition of :func:`snapshot`. Numeric values
    become gauges; string values (breaker states, paths) become
    ``<name>_info{value="..."} 1`` rows."""
    if snap is None:
        snap = snapshot(include_aggregates=False)
    lines = []
    for key in sorted(snap):
        val = snap[key]
        name, label = _prom_name(key)
        if isinstance(val, bool):
            val = int(val)
        if isinstance(val, (int, float)):
            if label is not None:
                lines.append(f'{name}{{key="{label}"}} {val}')
            else:
                lines.append(f"{name} {val}")
        elif val is not None:
            sval = str(val).replace("\\", "\\\\").replace('"', '\\"')
            lines.append(f'{name}_info{{value="{sval}"}} 1')
    return "\n".join(lines) + "\n"


def health():
    """Merged health payload over every registered serving session."""
    sessions = {}
    ready = True
    for s in list(_health_providers):
        try:
            sessions[s.name] = s.health()
            ready = ready and bool(s.ready())
        except Exception as e:  # noqa: BLE001 -- a probe must answer
            sessions[getattr(s, "name", "?")] = {"error": str(e)}
            ready = False
    return {"ready": ready, "sessions": sessions}


# -- stdlib HTTP endpoint ----------------------------------------------------

def _make_handler():
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib contract)
            try:
                if self.path.startswith("/metrics"):
                    body = render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4"
                    code = 200
                elif self.path.startswith("/healthz"):
                    h = health()
                    body = json.dumps(h).encode()
                    ctype = "application/json"
                    code = 200 if h["ready"] else 503
                elif self.path.startswith("/snapshot"):
                    body = json.dumps(snapshot(), default=str).encode()
                    ctype = "application/json"
                    code = 200
                else:
                    body = b"not found\n"
                    ctype = "text/plain"
                    code = 404
            except Exception as e:  # noqa: BLE001 -- scrape must answer
                body = f"export error: {e}\n".encode()
                ctype = "text/plain"
                code = 500
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # silence per-request stderr lines
            pass

    return Handler


def start_http(port=None, host="127.0.0.1"):
    """Serve /metrics + /healthz + /snapshot on a daemon thread; returns
    the bound port (``port=0`` binds an ephemeral one). Idempotent."""
    global _server, _server_thread
    with _server_lock:
        if _server is not None:
            return _server.server_address[1]
        from http.server import ThreadingHTTPServer

        if port is None:
            port = int(_cfg.get("MXNET_METRICS_PORT"))
        srv = ThreadingHTTPServer((host, int(port)), _make_handler())
        srv.daemon_threads = True

        def _serve():
            _core.register_thread_name()
            srv.serve_forever()

        th = threading.Thread(target=_serve,
                              name="mxtpu-metrics-http", daemon=True)
        th.start()
        _server, _server_thread = srv, th
        return srv.server_address[1]


def stop_http():
    global _server, _server_thread
    with _server_lock:
        if _server is None:
            return
        srv, th = _server, _server_thread
        _server = _server_thread = None
    # shutdown + join outside _server_lock: joining the serve thread
    # while holding the lock its handlers may want is an L002 hazard
    srv.shutdown()
    srv.server_close()
    th.join(5)


def server_port():
    with _server_lock:
        return None if _server is None else _server.server_address[1]


def maybe_start_from_env():
    """``MXNET_METRICS_PORT=<p>`` starts the endpoint at import (called
    from ``profiler.__init__``). Unset: nothing. Explicitly set to
    ``0``: bind an EPHEMERAL port — the bound port is reported back via
    a ``MXNET_METRICS_PORT_BOUND=<port>`` line on stderr (greppable by
    the harness that launched the process) and :func:`server_port`."""
    import os

    raw = os.environ.get("MXNET_METRICS_PORT")
    if raw is None or not raw.strip():
        return
    try:
        port = int(raw)
    except ValueError:
        return
    if port < 0:
        return
    try:
        bound = start_http(port)
    except OSError as e:
        import warnings

        warnings.warn(f"MXNET_METRICS_PORT={port}: could not start "
                      f"metrics endpoint: {e}", RuntimeWarning)
        return
    if port == 0:
        print(f"MXNET_METRICS_PORT_BOUND={bound}", file=sys.stderr,
              flush=True)
