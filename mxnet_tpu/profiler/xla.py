"""XLA-side capture: ``jax.profiler`` traces and per-op device tables.

``jax.profiler`` produces XPlane/perfetto traces of XLA execution (the role
of the reference engine's ``ProfileOperator``); the host event bus in
``core.py`` cannot see inside compiled programs, so device-time attribution
comes from here: :func:`device_op_stats` parses the chrome trace a capture
wrote (device pid rows carry ``device_duration_ps`` / ``model_flops`` /
``bytes_accessed`` per XLA op) into per-op tables — the role of the
reference's ``src/profiler/aggregate_stats.cc``.
"""
from __future__ import annotations

import os

from ..base import MXNetError

_trace_dir = None
_tracing = False


def trace_dir():
    """Directory of the last ``jax.profiler`` capture (None if never run)."""
    return _trace_dir


def start_trace(base_filename):
    """Start a ``jax.profiler`` trace next to ``base_filename``."""
    global _trace_dir, _tracing
    import jax

    if _tracing:
        return _trace_dir
    d = os.path.splitext(base_filename)[0] + "_trace"
    jax.profiler.start_trace(d)
    # published only on success: callers swallow start failures, and a
    # pre-assigned dir would make device_op_stats serve a STALE capture
    _trace_dir = d
    _tracing = True
    return _trace_dir


def stop_trace():
    global _tracing
    if not _tracing:
        return
    import jax

    jax.profiler.stop_trace()
    _tracing = False


def is_tracing():
    return _tracing


def device_op_stats(trace_dir_=None):
    """Per-op DEVICE time table from a captured trace.

    Parses the chrome-trace the ``jax.profiler`` run wrote (device pid rows
    carry ``device_duration_ps``/``model_flops``/``bytes_accessed`` per XLA
    op) and aggregates by op name. Returns rows sorted by total device time:
    ``{"name", "category", "calls", "total_us", "avg_us", "flops",
    "bytes_accessed", "tflops_s", "gb_s"}``.

    ``trace_dir_`` defaults to the directory of the last XLA capture. Empty
    list when the backend recorded no device events (pure-CPU runs expose
    host events only).
    """
    import glob
    import gzip
    import json

    d = trace_dir_ or _trace_dir
    if d is None:
        raise MXNetError(
            "no trace captured: run set_config(profile_xla=True); "
            "set_state('run') ... set_state('stop') first")
    paths = sorted(glob.glob(os.path.join(d, "**", "*.trace.json.gz"),
                             recursive=True))
    if not paths:
        return []
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # device pids are announced by process_name metadata like '/device:TPU:0'
    dev_pids = {e.get("pid") for e in events
                if e.get("ph") == "M" and e.get("name") == "process_name"
                and "/device:" in str(e.get("args", {}).get("name", ""))}
    agg = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        args = e.get("args", {})
        if "device_duration_ps" not in args:
            continue
        name = e.get("name", "?")
        row = agg.setdefault(name, {
            "name": name,
            "category": args.get("hlo_category", ""),
            "calls": 0, "total_us": 0.0, "flops": 0, "bytes_accessed": 0})
        row["calls"] += 1
        row["total_us"] += float(args["device_duration_ps"]) / 1e6
        row["flops"] += int(args.get("model_flops", 0) or 0)
        row["bytes_accessed"] += int(args.get("bytes_accessed", 0) or 0)
    rows = sorted(agg.values(), key=lambda r: -r["total_us"])
    for r in rows:
        r["avg_us"] = r["total_us"] / max(r["calls"], 1)
        secs = r["total_us"] / 1e6
        r["tflops_s"] = r["flops"] / secs / 1e12 if secs else 0.0
        r["gb_s"] = r["bytes_accessed"] / secs / 1e9 if secs else 0.0
    return rows


def device_op_table(trace_dir_=None, by_category=False, top=30):
    """Formatted per-op (or per-category) device-time table; the printable
    analog of ``MXAggregateProfileStatsPrint``."""
    rows = device_op_stats(trace_dir_)
    if by_category:
        cats = {}
        for r in rows:
            c = cats.setdefault(r["category"] or "other", {
                "name": r["category"] or "other", "calls": 0,
                "total_us": 0.0, "flops": 0, "bytes_accessed": 0})
            c["calls"] += r["calls"]
            c["total_us"] += r["total_us"]
            c["flops"] += r["flops"]
            c["bytes_accessed"] += r["bytes_accessed"]
        rows = sorted(cats.values(), key=lambda r: -r["total_us"])
        for r in rows:
            secs = r["total_us"] / 1e6
            r["tflops_s"] = r["flops"] / secs / 1e12 if secs else 0.0
            r["gb_s"] = r["bytes_accessed"] / secs / 1e9 if secs else 0.0
    lines = [f"{'Name':<32}{'Calls':>7}{'Total(us)':>12}"
             f"{'TFLOP/s':>9}{'GB/s':>8}"]
    for r in rows[:top]:
        lines.append(f"{r['name'][:31]:<32}{r['calls']:>7}"
                     f"{r['total_us']:>12.1f}{r['tflops_s']:>9.1f}"
                     f"{r['gb_s']:>8.0f}")
    return "\n".join(lines)
