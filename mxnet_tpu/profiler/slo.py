"""Declarative serving SLOs with multi-window error-budget burn rates.

An :class:`SLO` states an objective over one of the serving metric
families — inter-token latency p99, TTFT p99, goodput, error rate — as
``SLO(metric, target, window)``: "99% of ITL samples land under
``target`` ms over any ``window`` seconds". An :class:`SLOMonitor`
attaches to a :class:`~..serve.metrics.ServeMetrics` accumulator (one
``is None`` branch per observation when absent — the hot-path contract)
and evaluates every objective Google-SRE style over TWO windows:

* **burn rate** = (bad-event fraction in window) / (error budget),
  where the budget is ``1 - ratio`` (e.g. 0.01 for a p99 objective);
* an objective **burns** only when BOTH the fast window (default
  ``window / 12``, the 1h/5m shape scaled down) and the slow window
  exceed the threshold (default ``MXNET_SLO_BURN_THRESHOLD`` = 14.4,
  the classic fast-page rate) with at least ``MXNET_SLO_MIN_EVENTS``
  fast-window events — a sparse healthy run can't false-alarm.

Escalation rides the PR-9 flight recorder: the ok->burning edge dumps
reason ``slo_burn`` naming the violated objective (the recorder's own
per-reason rate limit and ``MXNET_FLIGHT_RECORDER_MAX_DUMPS`` cap bound
a storm to ONE dump). Gauges ``slo.burn_rate(...)`` /
``slo.budget_remaining(...)`` land on the profiler bus and the full
monitor state merges into ``export.snapshot()`` as ``slo.<name>.*``.
A burning monitor turns the ``/healthz`` surface **degraded, not
dead**: ``InferenceSession.health()`` carries the violation but
``ready()`` stays True — an SLO burn is a page, not a kill switch.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref

from . import core as _core
from . import recorder as _recorder

# live monitors, for export.snapshot() pull-discovery
_instances: "weakref.WeakSet" = weakref.WeakSet()

# metric family -> (feed kind, default good-ratio). Latency families
# judge each sample against the ms target at the implied quantile;
# ratio families judge completions, with the target AS the ratio.
_FAMILIES = {
    "itl_p99_ms": ("itl_ms", 0.99),
    "ttft_p99_ms": ("ttft_ms", 0.99),
    "goodput": ("completion", None),      # target = min good fraction
    "error_rate": ("completion", None),   # target = max error fraction
}


class SLO:
    """One declarative objective: ``SLO("itl_p99_ms", 50.0, 60.0)``
    reads "ITL p99 <= 50 ms over any 60 s window".

    Parameters
    ----------
    metric : one of ``itl_p99_ms`` / ``ttft_p99_ms`` / ``goodput`` /
        ``error_rate``.
    target : ms bound for the latency families; good-completion
        fraction for ``goodput`` (e.g. 0.99); max error fraction for
        ``error_rate`` (e.g. 0.01).
    window : slow evaluation window, seconds (``None`` =
        ``MXNET_SLO_WINDOW_S``).
    fast_window : fast window, seconds (default ``window / 12`` — the
        SRE 1h/5m ratio, scaled to whatever ``window`` is).
    threshold : burn-rate alert threshold over BOTH windows (default
        ``MXNET_SLO_BURN_THRESHOLD``).
    """

    __slots__ = ("metric", "target", "window", "fast_window", "threshold",
                 "ratio", "kind")

    def __init__(self, metric, target, window=None, fast_window=None,
                 threshold=None):
        from .. import config

        if metric not in _FAMILIES:
            from ..base import MXNetError

            raise MXNetError(
                f"unknown SLO metric {metric!r} (want one of "
                f"{sorted(_FAMILIES)})")
        self.metric = metric
        self.target = float(target)
        self.kind, ratio = _FAMILIES[metric]
        if window is None:
            window = float(config.get("MXNET_SLO_WINDOW_S"))
        self.window = float(window)
        self.fast_window = (float(fast_window) if fast_window is not None
                            else self.window / 12.0)
        if threshold is None:
            threshold = float(config.get("MXNET_SLO_BURN_THRESHOLD"))
        self.threshold = float(threshold)
        # error budget: the allowed bad-event fraction
        if ratio is not None:
            self.ratio = ratio                      # latency p99 family
        elif metric == "goodput":
            self.ratio = self.target                # target IS the ratio
        else:                                       # error_rate
            self.ratio = 1.0 - self.target
        self.ratio = min(max(self.ratio, 0.0), 1.0 - 1e-9)

    @property
    def budget(self):
        return 1.0 - self.ratio

    def good(self, value=None, ok=True, deadline_ok=True):
        """Is one observed event within this objective?"""
        if self.kind in ("itl_ms", "ttft_ms"):
            return float(value) <= self.target
        if self.metric == "goodput":
            return bool(ok) and bool(deadline_ok)
        return bool(ok)  # error_rate: any non-error completion is good

    def describe(self):
        return {"metric": self.metric, "target": self.target,
                "window_s": self.window, "fast_window_s": self.fast_window,
                "threshold": self.threshold, "budget": self.budget}


class SLOMonitor:
    """Multi-window burn-rate evaluator over a set of objectives.

    Feed it through :meth:`attach` (the ``ServeMetrics`` observation
    hooks call :meth:`observe`) or directly with explicit timestamps
    (the table-driven tests do). Evaluation is passive and amortized:
    at most once per ``MXNET_SLO_EVAL_INTERVAL_S`` on the observing
    thread — no extra threads, nothing to shut down.
    """

    def __init__(self, name, objectives, eval_interval=None,
                 min_events=None):
        from .. import config

        self.name = name
        self.objectives = list(objectives)
        if eval_interval is None:
            eval_interval = float(config.get("MXNET_SLO_EVAL_INTERVAL_S"))
        self._eval_interval = float(eval_interval)
        if min_events is None:
            min_events = int(config.get("MXNET_SLO_MIN_EVENTS"))
        self._min_events = int(min_events)
        self._lock = threading.Lock()
        # one timestamped (ts, good) ring per objective
        self._events = [collections.deque(maxlen=4096)
                        for _ in self.objectives]
        self._last_eval = 0.0
        self._state = "ok"
        self._violations = {}   # metric -> last evaluate() row
        self._last_eval_rows = []
        self.burns = 0          # cumulative ok->burning edges
        _instances.add(self)

    # -- feeding -------------------------------------------------------------
    def attach(self, serve_metrics):
        """Wire this monitor into a ``ServeMetrics`` accumulator's
        observation hooks; returns self for chaining."""
        serve_metrics.slo = self
        return self

    def observe(self, kind, value=None, ok=True, deadline_ok=True,
                ts=None):
        """One observed event of ``kind`` (``itl_ms`` / ``ttft_ms`` /
        ``completion``); routed to every objective of that family."""
        now = ts if ts is not None else time.monotonic()
        hit = False
        with self._lock:
            for i, obj in enumerate(self.objectives):
                if obj.kind != kind:
                    continue
                self._events[i].append(
                    (now, obj.good(value=value, ok=ok,
                                   deadline_ok=deadline_ok)))
                hit = True
        if hit and ts is None \
                and now - self._last_eval >= self._eval_interval:
            self.evaluate(now)

    # -- evaluation ----------------------------------------------------------
    def _window_rate(self, events, now, window):
        """(bad_fraction, n_events) over ``[now - window, now]``."""
        bad = n = 0
        for ts, good in reversed(events):
            if now - ts > window:
                break
            n += 1
            if not good:
                bad += 1
        return (bad / n if n else 0.0), n

    def evaluate(self, now=None):
        """Evaluate every objective's fast+slow burn rates; fires the
        ``slo_burn`` flight-recorder escalation on an ok->burning edge
        and refreshes the ``slo.*`` gauges. Returns the per-objective
        rows."""
        if now is None:
            now = time.monotonic()
        rows = []
        burning_metrics = []
        with self._lock:
            self._last_eval = now
            snap = [list(ev) for ev in self._events]
        for obj, events in zip(self.objectives, snap):
            bad_fast, n_fast = self._window_rate(events, now,
                                                 obj.fast_window)
            bad_slow, n_slow = self._window_rate(events, now, obj.window)
            burn_fast = bad_fast / obj.budget
            burn_slow = bad_slow / obj.budget
            # budget left in the slow window: 1 = untouched, 0 = spent
            budget_remaining = max(0.0, 1.0 - burn_slow)
            burning = (n_fast >= self._min_events
                       and burn_fast >= obj.threshold
                       and burn_slow >= obj.threshold)
            row = {"metric": obj.metric, "target": obj.target,
                   "burn_rate_fast": round(burn_fast, 4),
                   "burn_rate_slow": round(burn_slow, 4),
                   "budget_remaining": round(budget_remaining, 4),
                   "events_fast": n_fast, "events_slow": n_slow,
                   "threshold": obj.threshold, "burning": burning}
            rows.append(row)
            if burning:
                burning_metrics.append(row)
            if _core.ENABLED:
                tag = f"{self.name}:{obj.metric}"
                _core.set_counter(f"slo.burn_rate({tag})",
                                  round(burn_fast, 4), cat="slo")
                _core.set_counter(f"slo.budget_remaining({tag})",
                                  round(budget_remaining, 4), cat="slo")
        with self._lock:
            was = self._state
            self._state = "degraded" if burning_metrics else "ok"
            self._violations = {r["metric"]: r for r in burning_metrics}
            self._last_eval_rows = rows
            edge = burning_metrics and was == "ok"
            if edge:
                self.burns += 1
        if edge:
            # the recorder's per-reason rate limit + dump cap bound a
            # sustained storm to one dump; name the violated objective
            worst = max(burning_metrics,
                        key=lambda r: r["burn_rate_fast"])
            _recorder.note("escalation", f"slo.burn({self.name})",
                           {"metric": worst["metric"]})
            _recorder.dump("slo_burn", {
                "monitor": self.name,
                "objective": worst["metric"],
                "target": worst["target"],
                "burn_rate_fast": worst["burn_rate_fast"],
                "burn_rate_slow": worst["burn_rate_slow"],
                "violations": burning_metrics,
            })
        return rows

    # -- readout -------------------------------------------------------------
    @property
    def state(self):
        return self._state

    def health(self):
        """The ``/healthz`` fragment: degraded-not-dead."""
        with self._lock:
            return {"state": self._state,
                    "violations": sorted(self._violations),
                    "burns": self.burns}

    def snapshot(self):
        with self._lock:
            rows = list(self._last_eval_rows)
            state = self._state
            burns = self.burns
        out = {"state": state, "degraded": int(state == "degraded"),
               "burns": burns}
        for r in rows:
            m = r["metric"]
            out[f"{m}.burn_rate_fast"] = r["burn_rate_fast"]
            out[f"{m}.burn_rate_slow"] = r["burn_rate_slow"]
            out[f"{m}.budget_remaining"] = r["budget_remaining"]
            out[f"{m}.burning"] = int(r["burning"])
        return out


def all_snapshots():
    """``{monitor_name: snapshot()}`` over every live monitor."""
    return {m.name: m.snapshot() for m in list(_instances)}
