"""Runtime telemetry subsystem (reference: ``python/mxnet/profiler.py``
over ``src/profiler/``: chrome://tracing JSON, ``aggregate_stats`` tables,
``dumps()``/``get_summary()``).

Three layers:

* ``core``    — the in-process event bus: scoped ranges, counters, the
  chrome://tracing export (:func:`dump`) and the aggregate table
  (:func:`dumps`). Instrumentation hooks in ``cachedop.py`` (compile
  timing, cache hit/miss, recompile-storm warning), ``engine.py`` (wait
  stalls, async queue depth, and the deferred-dispatch segment counters:
  ``engine::bulk_flush`` ranges with reason/op-count args, the
  ``engine.bulk_flushes`` / ``engine.bulk_segment_ops`` gauges —
  cumulative totals incl. the flush-reason histogram and segment-cache
  hit rate live in ``engine.bulk_stats()``), ``kvstore/dist_tpu.py``
  (allreduce timing/bytes, AOT-compile split) and ``ops/registry.py``
  (per-op call counters under ``profile_imperative``) feed it. All hooks
  are near-zero-cost while stopped: a module-level bool guard per site.
* ``metrics`` — step-level training numbers: :func:`step_marker`,
  :class:`TrainingMetrics` (samples/s, tokens/s, MFU from a FLOP
  estimate), :func:`device_memory_stats`; ``bench.py`` consumes these.
* ``xla``     — ``jax.profiler`` capture (opt-in via
  ``set_config(profile_xla=True)``) and the per-op DEVICE-time tables
  :func:`device_op_stats` / :func:`device_op_table`.

Plus three observability layers over the bus (OBSERVABILITY.md):

* ``trace``    — request-scoped tracing (``MXNET_TRACE=1``): serving
  submits and training steps become chrome async/flow lanes connected by
  trace id across threads; ``trace.summary(trace_id)`` in-process.
* ``recorder`` — the always-on flight recorder (``MXNET_FLIGHT_RECORDER``,
  default on): a bounded ring of recent faults/sheds/warnings dumped to
  JSON automatically at escalation points (DivergenceError, MeshDegraded,
  quarantine, breaker-open, watchdog timeout).
* ``export``   — one ``snapshot()`` merging every subsystem's telemetry,
  rendered as Prometheus text and optionally served over HTTP
  (``MXNET_METRICS_PORT``): /metrics, /healthz, /snapshot.

Env vars (registered in ``mx.config``): ``MXNET_PROFILER_AUTOSTART=1``
starts the bus at import, ``MXNET_PROFILER_IMPERATIVE=1`` opts into per-op
dispatch counters, ``MXNET_CACHEDOP_SIG_LIMIT`` sets the recompile-storm
threshold.
"""
from __future__ import annotations

import contextlib
import time

from ..base import MXNetError
from . import attribution, core, export, metrics, recorder, slo, trace, xla
from .core import (aggregate_stats, register_thread_name, reset,
                   snapshot_events)
from .metrics import (
    TrainingMetrics,
    chip_peak,
    device_memory_stats,
    peak_flops,
    process_peak_bytes_in_use,
    step_marker,
    training_metrics,
)
from .xla import device_op_stats, device_op_table

_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_imperative": False,
    "profile_xla": False,
    "aggregate_stats": False,
}


def set_config(filename="profile.json", profile_all=False,
               profile_symbolic=True, profile_imperative=False,
               profile_memory=True, profile_api=True,
               aggregate_stats=False, profile_xla=False,
               **kwargs):  # pylint: disable=unused-argument
    """Configure output + capture scope (reference
    ``MXSetProcessProfilerConfig``). Divergences from the reference
    defaults, both deliberate: ``profile_imperative`` defaults to False
    (per-op dispatch counters cost a dict increment per eager call) and
    ``profile_xla=True`` opts into a ``jax.profiler`` device capture
    alongside the host event bus."""
    _config["filename"] = filename
    _config["profile_all"] = profile_all
    _config["profile_imperative"] = bool(profile_imperative or profile_all)
    _config["profile_xla"] = bool(profile_xla or profile_all)
    _config["aggregate_stats"] = aggregate_stats
    if core.ENABLED:
        core.IMPERATIVE = _config["profile_imperative"]


def _install_hooks():
    """Point the hot modules' ``_PROF`` slot at the event bus. Until the
    first ``set_state('run')`` those slots are ``None`` — a session that
    never profiles pays one ``is None`` test per dispatch site."""
    from .. import engine as _engine
    from ..ops import registry as _registry

    _engine._PROF = core
    _registry._PROF = core
    # phase-tagged engine:wait events need the attribution module's
    # thread-local phase even when the ledger itself is off
    _engine._ATTR = attribution


def set_state(state="stop", profile_process="worker"):  # pylint: disable=unused-argument
    """'run' starts the event bus (+ a jax.profiler capture when
    ``profile_xla``); 'stop' halts recording."""
    if state == "run":
        if not core.ENABLED:
            _install_hooks()
            core.start()
        core.IMPERATIVE = _config["profile_imperative"]
        # started even when the bus already runs (e.g. autostart before a
        # later set_config(profile_xla=True); set_state('run'))
        if _config["profile_xla"] and not xla.is_tracing():
            try:
                xla.start_trace(_config["filename"])
            except Exception:  # device capture is best-effort
                pass
    elif state == "stop":
        core.stop()
        xla.stop_trace()
    else:
        raise MXNetError(f"invalid profiler state {state!r}")


def state():
    return "run" if core.ENABLED else "stop"


def pause(profile_process="worker"):  # pylint: disable=unused-argument
    """Suspend recording without finalizing (reference ``MXProfilePause``).
    An active jax.profiler capture is finalized too — jax has no pause, so
    the device trace is closed out (resume() starts a fresh one)."""
    core.ENABLED = False
    core.IMPERATIVE = False
    xla.stop_trace()


def resume(profile_process="worker"):  # pylint: disable=unused-argument
    _install_hooks()
    core.ENABLED = True
    core.IMPERATIVE = _config["profile_imperative"]
    if _config["profile_xla"] and not xla.is_tracing():
        try:
            xla.start_trace(_config["filename"])
        except Exception:
            pass


def dump(finished=True, profile_process="worker"):  # pylint: disable=unused-argument
    """Write the chrome://tracing JSON to the configured filename and
    return its path (reference ``MXDumpProfile``). ``finished=True`` also
    stops an active capture first."""
    if finished:
        if core.ENABLED:
            set_state("stop")
        else:
            xla.stop_trace()  # paused session: finalize the device capture
    return core.dump(_config["filename"])


def dumps(reset=False):  # pylint: disable=redefined-outer-name
    """Aggregate host-side table: ranges by total time, imperative per-op
    call counts, counter gauges (reference
    ``MXAggregateProfileStatsPrint``)."""
    return core.dumps_table(reset_after=reset)


def get_summary(reset=False):  # pylint: disable=redefined-outer-name
    """Reference ``get_summary()``: the aggregate table as a string."""
    return core.dumps_table(reset_after=reset)


@contextlib.contextmanager
def scope(name="<unk>:", cat="scope"):
    """Named range: lands in the aggregate table always, in the chrome
    trace when running, and in the XLA device trace when one is active."""
    import jax

    t0 = time.perf_counter_ns()
    with jax.profiler.TraceAnnotation(name):
        yield
    core.record_duration(name, cat, t0)


class Task:
    """API-parity profiler objects (reference ``profiler.Task/Frame/
    Event``): named ranges you start/stop by hand."""

    def __init__(self, domain=None, name="task"):
        self.name = name
        self._t0 = None
        self._ann = None

    def start(self):
        import jax

        self._t0 = time.perf_counter_ns()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()

    def stop(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            core.record_duration(self.name, "task", self._t0)
            self._ann = None


Frame = Task
Event = Task


class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, f"{self.name}::{name}")

    def new_counter(self, name, value=0):
        return Counter(self, name, value)


class Counter:
    """Named counter; values land in the event bus as gauge events
    (reference ``profiler.Counter``)."""

    def __init__(self, domain=None, name="counter", value=0):
        self.name = name if domain is None else f"{domain.name}::{name}"
        self.value = value
        core.set_counter(self.name, value)

    def set_value(self, value):
        self.value = value
        core.set_counter(self.name, value)

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)


def start_server(*a, **k):  # pragma: no cover
    raise MXNetError("profiler server mode has no TPU analog; use "
                     "jax.profiler.start_server for live TensorBoard capture")


# MXNET_PROFILER_AUTOSTART: begin recording at import (the reference's
# profile_process-wide autostart env contract)
from .. import config as _cfg  # noqa: E402

if _cfg.get("MXNET_PROFILER_AUTOSTART"):
    set_config(profile_imperative=_cfg.get("MXNET_PROFILER_IMPERATIVE"))
    set_state("run")
elif _cfg.get("MXNET_PROFILER_IMPERATIVE"):
    set_config(profile_imperative=True)

# MXNET_TRACE=1: request-scoped tracing on from import (spans only land
# as chrome events while the bus records, but summaries work regardless)
if _cfg.get("MXNET_TRACE"):
    trace.enable(max_traces=_cfg.get("MXNET_TRACE_MAX"))

# MXNET_ATTRIBUTION=1: decode critical-path ledger on from import
if _cfg.get("MXNET_ATTRIBUTION"):
    attribution.enable()

# MXNET_METRICS_PORT=<p>: unified /metrics + /healthz endpoint at import
export.maybe_start_from_env()
