"""Request-scoped tracing: one logical request (a serving submit, a
training step) gets a :class:`Trace` whose spans are emitted as chrome
*async* events ('b'/'e' sharing the trace id) plus *flow* arrows
('s'/'f') at thread handoffs — so in Perfetto the request reads as one
connected lane across the batcher client thread, the flusher, and the
decode loop, no matter which tid did the work.

Propagation is explicit-or-ambient: producers that hold the ``Trace``
object call :func:`span_at` / :func:`flow_out` on it directly (the
batcher stores it on the pending entry), while nested callees that can't
see it (``InferenceSession.run`` under the batcher's runner, the
generator's decode step) use the thread-local *current trace* installed
by :func:`activate`.

Everything here is gated on the module-level ``ENABLED`` bool (set via
``MXNET_TRACE=1`` or :func:`enable`), mirroring the profiler hot-path
contract: a disabled tracer costs one attribute load and a branch per
site. Span *events* additionally require the profiler bus to be
recording (``core.ENABLED``) — the in-process summaries in the bounded
trace registry work either way.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time

from . import core as _core

ENABLED = False

_ids = itertools.count(1)
_flow_ids = itertools.count(1)
_lock = threading.Lock()
_registry: "collections.OrderedDict[int, Trace]" = collections.OrderedDict()
_max_traces = 1024
_tls = threading.local()
_step = 0  # global training-step tag (estimator bumps; dist_tpu reads)


def enable(max_traces=None):
    global ENABLED, _max_traces
    if max_traces is not None:
        _max_traces = max(1, int(max_traces))
    ENABLED = True


def disable():
    global ENABLED
    ENABLED = False


def reset():
    """Drop every registered trace (tests)."""
    with _lock:
        _registry.clear()
    _tls.stack = []


def set_step(n):
    """Tag subsequent collective events with training step ``n``."""
    global _step
    _step = int(n)


def current_step():
    return _step


class Trace:
    """One logical request: an id, a lane name, and its recorded spans."""

    __slots__ = ("trace_id", "name", "t0_ns", "t1_ns", "error", "finished",
                 "spans", "args", "_slock")

    def __init__(self, trace_id, name, args=None):
        self.trace_id = trace_id
        self.name = name
        self.t0_ns = time.perf_counter_ns()
        self.t1_ns = None
        self.error = None
        self.finished = False
        self.spans = []
        self.args = args
        self._slock = threading.Lock()

    # -- span / flow emission -----------------------------------------------
    def span_at(self, name, t0_ns, t1_ns, args=None):
        """Record a completed span retroactively from stored ns stamps
        (the batcher emits ``queue`` at dispatch time, ``execute`` at
        settle time). Thread-safe; callable from any thread."""
        tid = threading.get_ident() & 0xFFFFFFFF
        with self._slock:
            if not self.finished:
                self.spans.append({"name": name, "t0_ns": int(t0_ns),
                                   "t1_ns": int(t1_ns), "tid": tid,
                                   "args": args})
        if _core.ENABLED:
            pid = os.getpid()
            sid = str(self.trace_id)
            b = {"ph": "b", "cat": "trace", "name": name, "id": sid,
                 "pid": pid, "tid": tid,
                 "ts": round(_core._ts_us(t0_ns), 3)}
            if args:
                b["args"] = args
            _core.append_event(b)
            _core.append_event({"ph": "e", "cat": "trace", "name": name,
                                "id": sid, "pid": pid, "tid": tid,
                                "ts": round(_core._ts_us(t1_ns), 3)})

    def span(self, name, args=None):
        """Context manager recording one span around its body."""
        return _SpanCtx(self, name, args)

    def flow_out(self, name="handoff"):
        """Start a flow arrow at *this* thread/time; returns the flow id
        the receiving thread passes to :func:`flow_in`. Every issued id
        must eventually be closed (``flow_in``) so dumped traces carry no
        orphan arrows — close it on the shed/expired path too."""
        fid = next(_flow_ids)
        if _core.ENABLED:
            _core.append_event({
                "ph": "s", "cat": "trace.flow", "name": name,
                "id": str(fid), "pid": os.getpid(),
                "tid": threading.get_ident() & 0xFFFFFFFF,
                "ts": round(_core._ts_us(time.perf_counter_ns()), 3)})
        return fid

    def flow_in(self, fid, name="handoff"):
        """Finish a flow arrow on the receiving thread."""
        if fid and _core.ENABLED:
            _core.append_event({
                "ph": "f", "bp": "e", "cat": "trace.flow", "name": name,
                "id": str(fid), "pid": os.getpid(),
                "tid": threading.get_ident() & 0xFFFFFFFF,
                "ts": round(_core._ts_us(time.perf_counter_ns()), 3)})

    def finish(self, error=None):
        """Seal the trace (idempotent); later span_at calls are ignored."""
        with self._slock:
            if self.finished:
                return
            self.finished = True
            self.t1_ns = time.perf_counter_ns()
            if error is not None:
                self.error = str(error)

    def summary(self):
        """Per-trace readout: spans in record order plus per-name totals
        and the set of threads the request touched."""
        with self._slock:
            spans = list(self.spans)
            t1 = self.t1_ns
            err = self.error
            done = self.finished
        by_name = collections.defaultdict(lambda: [0, 0])
        tids = set()
        for s in spans:
            row = by_name[s["name"]]
            row[0] += 1
            row[1] += s["t1_ns"] - s["t0_ns"]
            tids.add(s["tid"])
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "finished": done,
            "error": err,
            "total_ms": (((t1 or time.perf_counter_ns()) - self.t0_ns)
                         / 1e6),
            "threads": len(tids),
            "spans": [{"name": s["name"],
                       "dur_ms": (s["t1_ns"] - s["t0_ns"]) / 1e6,
                       "tid": s["tid"], "args": s["args"]}
                      for s in spans],
            "by_name": {k: {"calls": v[0], "total_ms": v[1] / 1e6}
                        for k, v in by_name.items()},
        }


class _SpanCtx:
    __slots__ = ("_tr", "_name", "_args", "_t0")

    def __init__(self, tr, name, args):
        self._tr = tr
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        args = self._args
        if exc is not None:
            args = dict(args or ())
            args["error"] = type(exc).__name__
        self._tr.span_at(self._name, self._t0, time.perf_counter_ns(), args)
        return False


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL = _NullCtx()


# -- registry / ambient-trace API -------------------------------------------

def start_trace(name, args=None):
    """Create and register a new :class:`Trace`; ``None`` when tracing is
    off (every caller treats a ``None`` trace as "don't instrument")."""
    if not ENABLED:
        return None
    tr = Trace(next(_ids), name, args=args)
    with _lock:
        _registry[tr.trace_id] = tr
        while len(_registry) > _max_traces:
            _registry.popitem(last=False)
    return tr


def get(trace_id):
    with _lock:
        return _registry.get(trace_id)


def summary(trace_id):
    """In-process per-request span summary (``None`` if evicted/unknown)."""
    tr = get(trace_id)
    return tr.summary() if tr is not None else None


def summaries(limit=32):
    """Most recent ``limit`` trace summaries, newest last."""
    with _lock:
        traces = list(_registry.values())[-limit:]
    return [t.summary() for t in traces]


class _ActivateCtx:
    __slots__ = ("_tr",)

    def __init__(self, tr):
        self._tr = tr

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self._tr)
        return self._tr

    def __exit__(self, *a):
        _tls.stack.pop()
        return False


def activate(tr):
    """Make ``tr`` the calling thread's ambient trace for the ``with``
    body (no-op for a ``None`` trace)."""
    return _ActivateCtx(tr) if tr is not None else _NULL


def current():
    """The calling thread's ambient trace, or ``None``."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def span(name, args=None):
    """Span on the ambient trace; no-op context when none is active."""
    tr = current()
    return tr.span(name, args) if tr is not None else _NULL
