"""Always-on flight recorder: a cheap bounded ring of the most recent
notable events (faults, sheds, breaker transitions, warnings, counter
bumps worth keeping), dumped automatically to a timestamped JSON file at
the existing escalation points — ``DivergenceError``, ``MeshDegraded``,
checkpoint quarantine, circuit-breaker open, watchdog timeout — so the
moments *before* a crash are on disk even when nobody was profiling.

Unlike the profiler bus this runs regardless of ``core.ENABLED``: the
interesting traces are exactly the ones nobody started. The cost
contract mirrors PR 1's: with ``MXNET_FLIGHT_RECORDER=0`` every
:func:`note` is one module-bool check; enabled, it is a timestamp plus a
locked ``deque.append`` into a ``MXNET_FLIGHT_RECORDER_SIZE`` ring —
PERF.md documents the <5% bound on the eager microloop either way.

Dump files (``flightrec-<utcstamp>-<reason>.json`` under
``MXNET_FLIGHT_RECORDER_DIR``, default the system tempdir) carry the
ring, a profiler-counter snapshot (which includes the ``resilience.*``
mirror), and the escalation's own context. Automatic dumps are capped
per process (``MXNET_FLIGHT_RECORDER_MAX_DUMPS``) and rate-limited to
one per reason per second so an escalation storm can't fill a disk.
"""
from __future__ import annotations

import collections
import datetime
import json
import os
import tempfile
import threading
import time

from .. import config as _cfg
from . import core as _core

ENABLED = bool(_cfg.get("MXNET_FLIGHT_RECORDER"))

_lock = threading.Lock()
_ring: collections.deque = collections.deque(
    maxlen=max(1, int(_cfg.get("MXNET_FLIGHT_RECORDER_SIZE"))))
_seq = 0
_dumps = 0
_last_dump_path = None
_last_dump_by_reason: dict = {}  # reason -> monotonic s of last dump


def enable():
    global ENABLED
    ENABLED = True


def disable():
    global ENABLED
    ENABLED = False


def reset():
    """Clear the ring and the dump accounting (tests)."""
    global _seq, _dumps, _last_dump_path
    with _lock:
        _ring.clear()
        _seq = 0
        _dumps = 0
        _last_dump_path = None
        _last_dump_by_reason.clear()


def note(kind, name, args=None):
    """Append one ring entry. ``kind`` is the event class (``fault``,
    ``shed``, ``breaker``, ``warn``, ``counter``, ``escalation``...),
    ``name`` the specific site. Never raises."""
    global _seq
    if not ENABLED:
        return
    entry = {"t": time.time(), "thread": threading.current_thread().name,
             "kind": kind, "name": str(name)}
    if args:
        entry["args"] = args
    with _lock:
        _seq += 1
        entry["seq"] = _seq
        _ring.append(entry)


def snapshot():
    """Copy of the ring, oldest first."""
    with _lock:
        return list(_ring)


def last_dump_path():
    return _last_dump_path


def dump_count():
    return _dumps


def dump(reason, args=None, path=None, force=False):
    """Write the recorder state to JSON; returns the path, or ``None``
    when disabled / capped / rate-limited. Called from escalation hooks
    inside ``except`` blocks and error constructors, so it must never
    raise — any I/O failure is swallowed (and noted in the ring)."""
    global _dumps, _last_dump_path
    if not ENABLED and not force:
        return None
    reason = str(reason)
    now = time.monotonic()
    with _lock:
        if path is None:
            if _dumps >= int(_cfg.get("MXNET_FLIGHT_RECORDER_MAX_DUMPS")):
                return None
            last = _last_dump_by_reason.get(reason)
            if last is not None and now - last < 1.0 and not force:
                return None
        _last_dump_by_reason[reason] = now
        ring = list(_ring)
    doc = {
        "reason": reason,
        "args": args or {},
        "pid": os.getpid(),
        "utc": datetime.datetime.utcnow().isoformat() + "Z",
        "ring": ring,
        "counters": _core.counters_snapshot(),
        "dropped_profiler_events": _core._dropped,
    }
    try:
        from ..resilience import counters as _rescnt

        doc["resilience_counters"] = _rescnt.snapshot()
    except Exception:  # noqa: BLE001 -- forensics must not mask the error
        pass
    if path is None:
        d = _cfg.get("MXNET_FLIGHT_RECORDER_DIR") or tempfile.gettempdir()
        stamp = datetime.datetime.utcnow().strftime("%Y%m%dT%H%M%S.%f")
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in reason)[:48]
        path = os.path.join(d, f"flightrec-{stamp}-{safe}.json")
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f, default=str)
    except OSError as e:
        note("warn", "recorder.dump_failed", {"error": str(e)})
        return None
    with _lock:
        _dumps += 1
        _last_dump_path = path
    note("dump", reason, {"path": path})
    return path
