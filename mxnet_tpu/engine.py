"""Execution-engine facade.

The reference's dependency engine (``src/engine/threaded_engine.h``,
``include/mxnet/engine.h:117-318``) provides: (a) async execution of every op
with read/write dependency tracking, (b) ``WaitForVar``/``WaitForAll`` sync
points, (c) exception capture in async closures re-thrown at wait points, and
(d) bulk-execution segments.

On TPU all four come from XLA's async dispatch model:
  (a) ``jax`` enqueues device computations asynchronously and data dependencies
      are exact (SSA values), which is strictly stronger than var-queue
      tracking — there are no false WAR/WAW hazards because arrays are
      immutable under the hood (NDArray mutation rebinds a new buffer, the
      moral equivalent of the reference's ``Var::version_`` bump,
      ``include/mxnet/engine.h:44-61``).
  (b) ``wait_to_read`` maps to ``jax.Array.block_until_ready``.
  (c) XLA surfaces async device errors at block/transfer time; we re-raise
      them as ``MXNetError`` from the same wait points the reference uses
      (tested like ``tests/python/unittest/test_exc_handling.py``).
  (d) bulk-execution segments are REAL here: inside ``bulk(N)`` (or with
      ``MXNET_ENGINE_BULK_SIZE > 0``) imperative dispatch defers into
      per-thread segments flushed as one compiled executable each — see
      the "Deferred eager dispatch" section below.

``MXNET_ENGINE_TYPE=NaiveEngine`` gives fully synchronous execution for
debugging, as in the reference (``src/engine/naive_engine.cc``): every op
result is blocked on immediately after dispatch.
"""
from __future__ import annotations

import collections
import contextlib
import threading
import time
import weakref

from .base import MXNetError

_state = threading.local()

# telemetry hot-state (mxnet_tpu.profiler.core), installed by the first
# profiler.set_state('run'); None until then so unprofiled sessions pay a
# single `is None` test per site (see ops/registry.py)
_PROF = None

# fault-injection hot-state (resilience.faults.FaultPlan slot): None until
# a plan installs; wait points consult it so simulated async device errors
# surface exactly where contract (c) says real ones do
_FAULTS = None

# attribution hot-state (profiler.attribution module slot): None until the
# profiler package imports; wait points tag their stall events with the
# thread's active phase (decode/prefill/train/other) and, while the ledger
# is ENABLED, feed the stall duration into the per-phase wait accounting
_ATTR = None

# recently dispatched arrays (weakrefs): wait_all() drains these instead of
# blocking on every live array in the process (jax.live_arrays() is O(all
# arrays ever alive) — pathological when waitall() runs once per epoch).
# Tracking is per-thread (GIL-safe deque appends, no lock on the hot eager
# dispatch path); the registry of thread deques is what wait_all sweeps.
_PENDING_MAX = 4096
_pending_tls = threading.local()
_pending_registry = {}          # thread ident -> (thread weakref, deque)
_pending_orphans = collections.deque(maxlen=_PENDING_MAX)
_pending_lock = threading.Lock()  # guards registry + orphans


def _my_pending():
    dq = getattr(_pending_tls, "dq", None)
    if dq is None:
        dq = collections.deque(maxlen=_PENDING_MAX)
        _pending_tls.dq = dq
        ident = threading.get_ident()
        with _pending_lock:
            old = _pending_registry.get(ident)
            if old is not None:
                # ident reuse after a thread died: keep its undrained refs
                _pending_orphans.extend(old[1])
            _pending_registry[ident] = (
                weakref.ref(threading.current_thread()), dq)
    return dq


def track_async(arrays):
    """Record op outputs as outstanding async work for wait_all."""
    dq = _my_pending()
    for a in arrays:
        try:
            dq.append(weakref.ref(a))
        except TypeError:
            pass
    prof = _PROF
    if prof is not None and prof.ENABLED:
        # async queue depth gauge: outstanding dispatches on this thread
        prof.set_counter("engine.queue_depth", len(dq), cat="engine")


def engine_type() -> str:
    t = getattr(_state, "engine_type", None)
    if t is None:
        from . import config

        t = config.get("MXNET_ENGINE_TYPE")
        _state.engine_type = t
    return t


def set_engine_type(name: str):
    """'NaiveEngine' => synchronous op dispatch (debug aid)."""
    _state.engine_type = name


def is_naive() -> bool:
    return engine_type() == "NaiveEngine"


def maybe_sync(arrays):
    """Called by the dispatch layer after each op: tracks outputs for
    wait_all, and blocks immediately when NaiveEngine is on."""
    if is_naive():
        # already synced — nothing outstanding to track
        for a in arrays:
            try:
                a.block_until_ready()
            except AttributeError:
                pass
        return
    track_async(arrays)


def wait_for_var(data):
    """``Engine::WaitForVar`` analog: block until ``data`` is computed.
    The stall duration is recorded while the profiler runs."""
    flt = _FAULTS
    if flt is not None:
        # contract (c): injected async device errors surface at EVERY wait
        # point, not just wait_all (the reference re-throws engine
        # exceptions from WaitForVar and WaitForAll alike)
        flt.check("engine:wait")
    prof = _PROF
    attr = _ATTR
    profiling = prof is not None and prof.ENABLED
    attributing = attr is not None and attr.ENABLED
    if not profiling and not attributing:
        try:
            return data.block_until_ready()
        except AttributeError:
            return data
    t0 = time.perf_counter_ns()
    try:
        try:
            return data.block_until_ready()
        except AttributeError:
            return data
    finally:
        t1 = time.perf_counter_ns()
        phase = attr.current_phase() if attr is not None else "other"
        if attributing:
            attr.note_wait(t1 - t0, phase)
        if profiling:
            prof.record_duration("engine::wait_for_var", "engine", t0, t1,
                                 args={"phase": phase})


def _block_settled(a):
    """Block on one tracked array. Returns ``'ok'``, ``'skip'``, or the
    failure exception. Donated-away buffers (fused optimizer /
    static_alloc donate arrays that were tracked as op outputs — blocking
    on one raises 'Array has been deleted', including the race where the
    delete lands after the ``is_deleted`` check) and non-waitable strays
    are skips, not failures."""
    try:
        is_deleted = getattr(a, "is_deleted", None)
        if is_deleted is not None and is_deleted():
            return "skip"
        a.block_until_ready()
        return "ok"
    except AttributeError:
        return "skip"  # no block_until_ready: not async work
    except Exception as e:
        if "deleted" in str(e).lower():
            return "skip"
        return e


def wait_all():
    """``MXNDArrayWaitAll`` analog: drain outstanding async work.

    Blocks on the recently-dispatched set (bounded deque of weakrefs) —
    O(recent ops), not O(live arrays). ``MXNET_WAITALL_FULL=1`` restores
    the exhaustive ``jax.live_arrays()`` sweep for debugging.

    Contract (c) of the module docstring: async device errors re-raise at
    wait points. The FIRST failure encountered while draining is kept and
    re-raised as ``MXNetError`` after the drain completes — every other
    outstanding array is still waited on first, so one poisoned dispatch
    doesn't leave the rest of the queue untracked for the next wait_all.
    """
    import jax

    from . import config

    prof = _PROF
    attr = _ATTR
    profiling = prof is not None and prof.ENABLED
    attributing = attr is not None and attr.ENABLED
    t0 = time.perf_counter_ns() if profiling or attributing else 0
    drained = 0
    first_failure = None
    try:
        flush_all("wait")
    except Exception as e:
        first_failure = e  # re-raised below, after the drain completes
    flt = _FAULTS
    if flt is not None:
        flt.check("engine:wait")
    try:
        jax.effects_barrier()
    except AttributeError:
        pass  # jax version without effects_barrier
    except Exception as e:
        first_failure = e
    if config.get("MXNET_WAITALL_FULL"):
        try:
            live = jax.live_arrays()
        except Exception:
            live = []
        for a in live:
            r = _block_settled(a)
            if r == "ok":
                drained += 1
            elif r != "skip" and first_failure is None:
                first_failure = r
        if t0:
            t1 = time.perf_counter_ns()
            phase = attr.current_phase() if attr is not None else "other"
            if attributing:
                attr.note_wait(t1 - t0, phase)
            if profiling:
                prof.record_duration(
                    "engine::wait_all", "engine", t0, t1,
                    args={"mode": "full", "phase": phase,
                          "failed": first_failure is not None})
    else:
        with _pending_lock:
            deques = [dq for _, dq in _pending_registry.values()]
            deques.append(_pending_orphans)
            # prune registry entries for dead threads (their deques were
            # just captured above and get drained below) — no per-thread
            # leak
            dead = []
            for ident, (tref, _dq) in _pending_registry.items():
                t = tref()  # bind once: the second deref could race GC
                if t is None or not t.is_alive():
                    dead.append(ident)
            for ident in dead:
                del _pending_registry[ident]
        for dq in deques:
            while True:
                try:
                    ref = dq.popleft()
                except IndexError:
                    break
                a = ref()
                if a is None:
                    continue
                r = _block_settled(a)
                if r == "ok":
                    drained += 1
                elif r != "skip" and first_failure is None:
                    first_failure = r
        if t0:
            t1 = time.perf_counter_ns()
            phase = attr.current_phase() if attr is not None else "other"
            if attributing:
                attr.note_wait(t1 - t0, phase)
            if profiling:
                prof.record_duration(
                    "engine::wait_all", "engine", t0, t1,
                    args={"drained": drained, "phase": phase,
                          "failed": first_failure is not None})
                prof.set_counter("engine.queue_depth", 0, cat="engine")
    if first_failure is not None:
        raise MXNetError(
            f"async operation failed, surfaced at wait_all: "
            f"{type(first_failure).__name__}: {first_failure}"
        ) from first_failure


# ---------------------------------------------------------------------------
# Deferred eager dispatch: REAL bulk-execution segments.
#
# Inside an active ``bulk(N)`` scope (or with ``MXNET_ENGINE_BULK_SIZE > 0``
# globally), ``ops/registry.apply`` stops dispatching each op over the
# tunnel and instead records (op, static key, input handles) into the
# thread's pending :class:`_Segment`, handing back NDArrays backed by
# :class:`_LazyRef` placeholders.  The segment flushes as ONE jitted
# executable — the reference's bulk-execution segments
# (``Engine::StartBulk``/``StopBulk``, engine.h:311-317) done the XLA way —
# when it reaches N ops, when any lazy value is materialized, at wait
# points, at autograd tape boundaries, and before any op the recorder
# can't defer.  Flushed segments compile through ``_SEG_CACHE`` keyed on
# the sequence of per-op static keys + wiring, so a steady-state eager
# training loop replays one cached executable per segment instead of ~N
# per-op executables (~N tunnel RTTs).
#
# NaiveEngine forces the effective segment size to 1 (synchronous per-op
# semantics preserved); bulk size is THREAD-LOCAL — one thread's ``bulk()``
# scope can never change another thread's flush threshold mid-step.
# ---------------------------------------------------------------------------

_bulk_tls = threading.local()
# fast gate read by ops/registry.apply per dispatch: False until the first
# bulk activation (env knob at import, or any set_bulk_size(>1)/bulk()) —
# the default-off eager path pays ONE module-attribute test per op
try:
    import os as _os

    _BULK_POSSIBLE = int(_os.environ.get("MXNET_ENGINE_BULK_SIZE",
                                         "0") or 0) > 1
except ValueError:
    _BULK_POSSIBLE = False
_env_bulk = None        # cached MXNET_ENGINE_BULK_SIZE (process default)

# segment executable caches: one compiled replay (and one compiled vjp) per
# recorded op-sequence identity.  Same clear-don't-evict runaway discipline
# as registry._EAGER_JIT_CACHE.
_SEG_CACHE = {}
_SEG_BWD_CACHE = {}
_SEG_SKIP = set()       # segment keys whose trace consumed RNG: never cache
_SEG_CACHE_MAX = 512

# every live (possibly pending) segment, any thread: wait_all's drain-all
# contract extends to segments recorded on OTHER threads — flush is
# lock-protected and owners recover via record()'s None-restart, so a
# cross-thread flush here is safe
_live_segments = weakref.WeakSet()

# executable-invocation counter: every actual device dispatch — per-op
# apply, segment flush, backward tape-node invocation — bumps this.  The
# bench's dispatches-per-step column and the bulk conformance tests read it.
_dispatch_n = 0

# cumulative segment telemetry (cheap: only touched at flush, never on the
# per-op record path); bulk_stats() exposes it, profiler counters mirror it
_BULK_STATS = {
    "flushes": 0, "ops_flushed": 0, "cache_hits": 0, "cache_misses": 0,
    "cache_clears": 0, "reasons": collections.Counter(),
}


def _count_dispatch(n=1):
    global _dispatch_n
    _dispatch_n += n


def dispatch_count() -> int:
    """Executable invocations so far (per-op dispatches + segment flushes
    + backward tape-node invocations)."""
    return _dispatch_n


def reset_dispatch_count():
    global _dispatch_n
    _dispatch_n = 0


def bulk_stats(reset=False):
    """Segment-dispatch telemetry: flush count, ops bulked, per-reason
    flush histogram, and segment-cache hit/miss counts."""
    out = {
        "flushes": _BULK_STATS["flushes"],
        "ops_flushed": _BULK_STATS["ops_flushed"],
        "cache_hits": _BULK_STATS["cache_hits"],
        "cache_misses": _BULK_STATS["cache_misses"],
        "cache_clears": _BULK_STATS["cache_clears"],
        "reasons": dict(_BULK_STATS["reasons"]),
        "ops_per_flush": (_BULK_STATS["ops_flushed"] /
                          _BULK_STATS["flushes"]
                          if _BULK_STATS["flushes"] else 0.0),
    }
    if reset:
        _BULK_STATS.update(flushes=0, ops_flushed=0, cache_hits=0,
                           cache_misses=0, cache_clears=0,
                           reasons=collections.Counter())
    return out


def _env_bulk_size() -> int:
    global _env_bulk, _BULK_POSSIBLE
    if _env_bulk is None:
        from . import config

        try:
            _env_bulk = int(config.get("MXNET_ENGINE_BULK_SIZE") or 0)
        except (ValueError, TypeError):
            _env_bulk = 0
        if _env_bulk > 1:
            _BULK_POSSIBLE = True
    return _env_bulk


def set_bulk_size(size):
    """Set this THREAD's bulk-execution size limit (reference
    ``python/mxnet/engine.py:25``); returns the previous value.  A size
    > 1 turns on deferred eager dispatch for this thread; any pending
    segment is flushed on every change so a resize can never reorder ops
    across the boundary."""
    global _BULK_POSSIBLE
    prev = getattr(_bulk_tls, "size", None)
    if prev is None:
        prev = _env_bulk_size()
    size = int(size)
    if size != prev:
        flush_current("scope")
    _bulk_tls.size = size
    if size > 1:
        _BULK_POSSIBLE = True
    return prev


@contextlib.contextmanager
def bulk(size: int = 16):
    """Bulk-execution scope (``engine.h:311-317``): ops recorded inside
    defer into segments of up to ``size`` ops, each flushed as one
    compiled executable.  The scope duration and size are recorded while
    profiling; exit flushes the pending segment."""
    prev = set_bulk_size(size)
    prof = _PROF
    t0 = prof.begin() if prof is not None and prof.ENABLED else 0
    try:
        yield
    finally:
        set_bulk_size(prev)  # flushes the pending segment on change
        flush_current("scope")  # ... and when prev == size
        if t0:
            prof.record_duration("engine::bulk", "engine", t0,
                                 args={"size": size})


def _active_bulk_size() -> int:
    """Effective segment capacity for THIS thread right now; 0 when
    deferral is off (size <= 1, or NaiveEngine's forced size-1
    synchronous semantics)."""
    size = getattr(_bulk_tls, "size", None)
    if size is None:
        size = _env_bulk_size()
        _bulk_tls.size = size
    if size <= 1 or is_naive():
        return 0
    return size


def _segment_for_record(size) -> "_Segment":
    """The thread's open segment, creating one at ``size`` capacity if the
    previous segment flushed (or none exists)."""
    seg = getattr(_bulk_tls, "seg", None)
    if seg is None or seg.done:
        seg = _Segment(size)
        _bulk_tls.seg = seg
        _live_segments.add(seg)
    return seg


def flush_current(reason="manual"):
    """Flush this thread's pending segment, if any (no-op when bulking has
    never been activated)."""
    if not _BULK_POSSIBLE:
        return
    seg = getattr(_bulk_tls, "seg", None)
    if seg is not None and not seg.done:
        seg.flush(reason)


def flush_all(reason="wait"):
    """Flush EVERY thread's pending segment (wait_all's drain-all
    contract: deferred work recorded on other threads must be submitted
    — and its errors surfaced — before wait_all returns)."""
    if not _BULK_POSSIBLE:
        return
    first_failure = None
    for seg in list(_live_segments):
        if not seg.done:
            try:
                seg.flush(reason)
            except BaseException as e:  # surface ONE, flush the rest
                if first_failure is None:
                    first_failure = e
    if first_failure is not None:
        raise first_failure


class _LazyRef:
    """Placeholder buffer for one deferred op output.

    An NDArray whose ``_buf`` is a ``_LazyRef`` owns a value that does not
    exist yet; any ``_data`` access forces the owning segment to flush
    (shape/dtype are answered from the recorded aval without flushing).
    """

    __slots__ = ("seg", "idx", "shape", "dtype", "value", "err", "tainted",
                 "owner")

    def __init__(self, seg, idx, shape, dtype):
        self.seg = seg
        self.idx = idx
        self.shape = tuple(shape)
        self.dtype = dtype
        self.value = None   # concrete jax.Array once the segment flushed
        self.err = None     # the flush failure, surfaced at materialization
        self.tainted = False  # produced by a recorded (tape-tracked) op
        self.owner = None   # weakref to the NDArray handle (tape wiring)

    @property
    def ndim(self):
        return len(self.shape)

    def force(self):
        """Materialize: flush the owning segment and return the value."""
        seg = self.seg
        if self.value is None and self.err is None and seg is not None:
            seg.flush("materialize")
        if self.err is not None:
            raise MXNetError(
                f"deferred bulk segment failed; error surfaced at "
                f"materialization: {type(self.err).__name__}: {self.err}"
            ) from self.err
        return self.value


class _SegOp:
    """One recorded call: closed callable + static key + slot wiring."""

    __slots__ = ("closed", "key", "wiring", "out_slots", "single",
                 "was_list", "recorded", "name")

    def __init__(self, closed, key, wiring, out_slots, single, was_list,
                 recorded, name):
        self.closed = closed
        self.key = key
        self.wiring = wiring      # per input: ("i", slot) | ("e", ext_idx)
        self.out_slots = out_slots
        self.single = single
        self.was_list = was_list
        self.recorded = recorded
        self.name = name


_fence_fn = None


def _fence(flat):
    """Differentiable per-op fusion fence: ``optimization_barrier`` on the
    forward values AND on the backward cotangents (the raw primitive has
    no differentiation rule), with float0 cotangents passed through."""
    global _fence_fn
    if _fence_fn is None:
        import jax

        @jax.custom_vjp
        def fence(xs):
            return jax.lax.optimization_barrier(xs)

        def fence_fwd(xs):
            return jax.lax.optimization_barrier(xs), None

        def fence_bwd(_, cts):
            def b(c):
                if c is None or getattr(c, "dtype", None) == \
                        jax.dtypes.float0:
                    return c
                return jax.lax.optimization_barrier(c)

            return (tuple(b(c) for c in cts),)

        fence.defvjp(fence_fwd, fence_bwd)
        _fence_fn = fence
    return _fence_fn(flat)


_bulk_fuse_cached = None


def _bulk_fuse() -> bool:
    """MXNET_ENGINE_BULK_FUSE: let XLA fuse ACROSS the ops of a segment.
    Off by default: bulking batches *dispatch* (one tunnel RTT per
    segment), and per-op optimization barriers pin each op's numerics to
    its standalone executable so bulk-vs-unbulked results stay
    bitwise-identical. Fusing across ops can shave memory traffic at the
    cost of last-ulp drift in fused reductions."""
    global _bulk_fuse_cached
    if _bulk_fuse_cached is None:
        from . import config

        try:
            _bulk_fuse_cached = bool(config.get("MXNET_ENGINE_BULK_FUSE"))
        except Exception:
            _bulk_fuse_cached = False
    return _bulk_fuse_cached


def _build_replay(ops, n_slots):
    """The segment's forward as one traceable function of the external
    inputs. Rebuilt only on a segment-cache miss.

    Non-recorded ops get ``stop_gradient`` on their outputs: in unbulked
    eager an op outside ``autograd.record()`` (or under ``pause()``)
    produces a tape-less CONSTANT, so the segment vjp must not conduct
    gradient through it either. Identity in the forward, so sharing the
    forward executable across recorded-flag variations stays sound (the
    backward cache key pins the flags via ``rec_slots``).
    """
    barrier = not _bulk_fuse()

    def replay(*ext):
        import jax

        vals = [None] * n_slots
        for op in ops:
            ins = [vals[i] if tag == "i" else ext[i]
                   for tag, i in op.wiring]
            r = op.closed(*ins)
            if op.single:
                flat = (r,)
            else:
                flat = tuple(r)
            if not op.recorded:
                flat = jax.lax.stop_gradient(flat)
            if barrier:
                # fence each op: one executable per SEGMENT, but each op
                # keeps the exact numerics of its standalone dispatch
                flat = _fence(flat)
            for si, v in zip(op.out_slots, flat):
                vals[si] = v
        return tuple(vals)

    return replay


class _Segment:
    """A per-thread pending bulk segment: the recorded-but-not-dispatched
    op sequence plus its lazy output slots and pinned external inputs."""

    def __init__(self, size):
        self.size = size
        self.ops = []
        self.slots = []          # _LazyRef per flat output, in record order
        self.ext_vals = []       # pinned external jax.Arrays, in first-use order
        self.ext_ids = {}        # id(jax.Array) -> ext index
        self.ext_tracked = {}    # ext index -> (_slot_of(nd), nd) at record
        self.done = False
        self._lock = threading.RLock()
        self._eager_vjp = None   # exact vjp for uncacheable (RNG) segments

    # -- record (called from ops/registry on the owner thread) ------------
    def record(self, closed, key, ins, arrays, tracked_flags, avals,
               single, was_list, recorded, name):
        """Append one op; returns its lazy output refs, or ``None`` when a
        cross-thread materialization flushed this segment concurrently
        (the caller restarts on a fresh segment)."""
        with self._lock:
            if self.done:
                return None
            return self._record_locked(
                closed, key, ins, arrays, tracked_flags, avals,
                single, was_list, recorded, name)

    def _record_locked(self, closed, key, ins, arrays, tracked_flags,
                       avals, single, was_list, recorded, name):
        wiring = []
        for x, nd, tr in zip(ins, arrays, tracked_flags):
            if type(x) is _LazyRef:
                wiring.append(("i", x.idx))
            else:
                ei = self.ext_ids.get(id(x))
                if ei is None:
                    ei = len(self.ext_vals)
                    self.ext_vals.append(x)
                    self.ext_ids[id(x)] = ei
                wiring.append(("e", ei))
                if recorded and tr and ei not in self.ext_tracked:
                    from .ndarray.ndarray import _slot_of

                    self.ext_tracked[ei] = (_slot_of(nd), nd)
        base = len(self.slots)
        out_refs = []
        for k, (shape, dtype) in enumerate(avals):
            ref = _LazyRef(self, base + k, shape, dtype)
            ref.tainted = recorded
            self.slots.append(ref)
            out_refs.append(ref)
        self.ops.append(_SegOp(
            closed, key, tuple(wiring),
            tuple(range(base, base + len(avals))),
            single, was_list, recorded, name))
        return out_refs

    # -- flush ------------------------------------------------------------
    def flush(self, reason):
        with self._lock:
            if self.done:
                return
            self.done = True
            if not self.ops:
                return
            try:
                self._execute(reason)
            except BaseException as e:
                # poison every unfilled slot: the error re-surfaces at each
                # later materialization, like a real async device failure
                for s in self.slots:
                    if s.value is None and s.err is None:
                        s.err = e
                        s.seg = None
                raise

    def _execute(self, reason):
        import jax

        from . import random as _rng

        prof = _PROF
        t0 = prof.begin() if prof is not None and prof.ENABLED else 0
        flt = _FAULTS
        if flt is not None:
            # the per-op dispatch fault site still fires once per RECORDED
            # op — deferral must not make injected dispatch faults vanish;
            # they surface here, at the flush (= async) boundary
            for _op in self.ops:
                flt.check("op:dispatch")
        skey = tuple((op.key, op.wiring, len(op.out_slots))
                     for op in self.ops)
        rec_slots = tuple(si for op in self.ops if op.recorded
                          for si in op.out_slots)
        ext = tuple(self.ext_vals)
        tracked_idx = tuple(sorted(self.ext_tracked))
        _count_dispatch()
        hit = False
        if skey in _SEG_SKIP:
            if rec_slots:
                out_flat = self._run_eager_vjp(ext, tracked_idx)
            else:
                out_flat = _build_replay(self.ops, len(self.slots))(*ext)
        else:
            cached = _SEG_CACHE.get(skey)
            if cached is not None:
                hit = True
                out_flat = cached(*ext)
            else:
                replay = _build_replay(self.ops, len(self.slots))
                mark = _rng.consume_count()
                jitted = jax.jit(replay)
                out_flat = jitted(*ext)
                if _rng.consume_count() == mark:
                    if len(_SEG_CACHE) >= _seg_cache_max():
                        _SEG_CACHE.clear()
                        _SEG_BWD_CACHE.clear()
                        # attributable, like the registry cache clears:
                        # churning segment shapes re-pay compiles
                        _BULK_STATS["cache_clears"] += 1
                        from .ops.registry import _note_cache_clear

                        _note_cache_clear(
                            "bulk segment cache", "seg_cache_clears",
                            _BULK_STATS["cache_clears"],
                            limit=_seg_cache_max())
                    _SEG_CACHE[skey] = jitted
                else:
                    # the trace drew RNG keys: a cached replay would bake
                    # them forever. If the segment is on the tape, redo it
                    # under an exact residual-carrying vjp so backward
                    # replays the SAME keys this forward used.
                    _SEG_SKIP.add(skey)
                    if rec_slots:
                        _count_dispatch()
                        out_flat = self._run_eager_vjp(ext, tracked_idx)
        for s, v in zip(self.slots, out_flat):
            s.value = v
            s.seg = None
        maybe_sync(out_flat)
        if rec_slots:
            self._record_tape_node(skey, rec_slots, tracked_idx, ext)
        stats = _BULK_STATS
        stats["flushes"] += 1
        stats["ops_flushed"] += len(self.ops)
        stats["reasons"][reason] += 1
        stats["cache_hits" if hit else "cache_misses"] += 1
        if t0:
            prof.record_duration("engine::bulk_flush", "engine", t0,
                                 args={"reason": reason,
                                       "ops": len(self.ops),
                                       "cached": hit})
            prof.incr_counter("engine.bulk_flushes", cat="engine")
            prof.set_counter("engine.bulk_segment_ops", len(self.ops),
                             cat="engine")

    def _run_eager_vjp(self, ext, tracked_idx):
        """Uncacheable (RNG-consuming) recorded segment: run the forward
        under plain ``jax.vjp`` so the stored backward carries the exact
        residuals (a remat would re-draw keys and mismatch the masks)."""
        import jax

        replay = _build_replay(self.ops, len(self.slots))

        def f(*tr):
            full = list(ext)
            for i, v in zip(tracked_idx, tr):
                full[i] = v
            return replay(*full)

        out_flat, vjp = jax.vjp(f, *(ext[i] for i in tracked_idx))
        self._eager_vjp = vjp
        return out_flat

    def _record_tape_node(self, skey, rec_slots, tracked_idx, ext):
        """Transparent passthrough under tape: the flushed segment joins
        the autograd tape as ONE node (the bulk analog of a hybridized
        CachedOp node) whose backward is one compiled vjp per segment
        key — same remat discipline as ``registry._make_cached_vjp``."""
        from . import autograd as _ag

        n_ext = len(ext)
        untracked_idx = tuple(i for i in range(n_ext)
                              if i not in set(tracked_idx))
        tracked_vals = tuple(ext[i] for i in tracked_idx)
        untracked_vals = tuple(ext[i] for i in untracked_idx)
        ops = self.ops
        n_slots = len(self.slots)
        slot_avals = [(self.slots[i].shape, self.slots[i].dtype)
                      for i in rec_slots]

        if self._eager_vjp is not None:
            raw_vjp = self._eager_vjp
            all_avals = [(s.shape, s.dtype) for s in self.slots]

            def vjp_fn(cts):
                import jax
                import jax.numpy as jnp

                if not isinstance(cts, tuple):
                    cts = (cts,)
                full = [jnp.zeros(sh, dt) for sh, dt in all_avals]
                for ct, si in zip(cts, rec_slots):
                    full[si] = ct
                out = raw_vjp(tuple(full))
                return tuple(
                    None if (hasattr(c, "dtype")
                             and c.dtype == jax.dtypes.float0) else c
                    for c in out)
        else:
            bkey = (skey, tracked_idx, rec_slots)

            def vjp_fn(cts):
                import jax

                if not isinstance(cts, tuple):
                    cts = (cts,)
                bwd = _SEG_BWD_CACHE.get(bkey)
                if bwd is None:
                    replay = _build_replay(ops, n_slots)

                    def bwd_fn(cts_, tr, untr):
                        def f(*trr):
                            full = [None] * n_ext
                            for i, v in zip(tracked_idx, trr):
                                full[i] = v
                            for i, v in zip(untracked_idx, untr):
                                full[i] = v
                            vals = replay(*full)
                            return tuple(vals[i] for i in rec_slots)

                        _, vjp = jax.vjp(f, *tr)
                        out = vjp(cts_)
                        return tuple(
                            None if (hasattr(c, "dtype")
                                     and c.dtype == jax.dtypes.float0)
                            else c
                            for c in out)

                    bwd = jax.jit(bwd_fn)
                    _SEG_BWD_CACHE[bkey] = bwd
                return bwd(cts, tracked_vals, untracked_vals)

        def fwd_fn(*tr):
            # create_graph=True support: the segment's recorded outputs as
            # a function of its tracked inputs (untracked closed over —
            # they are fixed concrete values of THIS flush)
            replay = _build_replay(ops, n_slots)
            full = [None] * n_ext
            for i, v in zip(tracked_idx, tr):
                full[i] = v
            for i in untracked_idx:
                full[i] = ext[i]
            vals = replay(*full)
            return tuple(vals[i] for i in rec_slots)

        node = _ag.TapeNode(
            vjp_fn,
            [self.ext_tracked[i][0] for i in tracked_idx],
            slot_avals,
            name=f"bulk_segment[{len(ops)}]",
            fwd_fn=fwd_fn,
            in_arrays=[self.ext_tracked[i][1] for i in tracked_idx],
        )
        node.out_container = True
        for k, si in enumerate(rec_slots):
            owner = self.slots[si].owner
            nd = owner() if owner is not None else None
            if nd is not None:
                nd._tape = (node, k)


_seg_cache_max_cached = None


def _seg_cache_max() -> int:
    global _seg_cache_max_cached
    if _seg_cache_max_cached is None:
        from . import config

        try:
            _seg_cache_max_cached = int(
                config.get("MXNET_ENGINE_SEG_CACHE_MAX"))
        except Exception:
            _seg_cache_max_cached = _SEG_CACHE_MAX
    return _seg_cache_max_cached


# ---------------------------------------------------------------------------
# Raw engine push API parity (``MXEnginePushAsync/Sync``, c_api.h:3028-3110).
# External schedulers in the reference can push closures with explicit var
# deps. Here ordering is data-flow exact, so push == call.
# ---------------------------------------------------------------------------


def push_sync(fn, *args, **kwargs):
    return fn(*args, **kwargs)


def push_async(fn, *args, on_complete=None, **kwargs):
    out = fn(*args, **kwargs)
    if on_complete is not None:
        on_complete()
    return out
