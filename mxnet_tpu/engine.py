"""Execution-engine facade.

The reference's dependency engine (``src/engine/threaded_engine.h``,
``include/mxnet/engine.h:117-318``) provides: (a) async execution of every op
with read/write dependency tracking, (b) ``WaitForVar``/``WaitForAll`` sync
points, (c) exception capture in async closures re-thrown at wait points, and
(d) bulk-execution segments.

On TPU all four come from XLA's async dispatch model:
  (a) ``jax`` enqueues device computations asynchronously and data dependencies
      are exact (SSA values), which is strictly stronger than var-queue
      tracking — there are no false WAR/WAW hazards because arrays are
      immutable under the hood (NDArray mutation rebinds a new buffer, the
      moral equivalent of the reference's ``Var::version_`` bump,
      ``include/mxnet/engine.h:44-61``).
  (b) ``wait_to_read`` maps to ``jax.Array.block_until_ready``.
  (c) XLA surfaces async device errors at block/transfer time; we re-raise
      them as ``MXNetError`` from the same wait points the reference uses
      (tested like ``tests/python/unittest/test_exc_handling.py``).
  (d) fusion/bulking is XLA's job (and ``hybridize``'s); the bulk context
      managers are kept as no-ops for API parity.

``MXNET_ENGINE_TYPE=NaiveEngine`` gives fully synchronous execution for
debugging, as in the reference (``src/engine/naive_engine.cc``): every op
result is blocked on immediately after dispatch.
"""
from __future__ import annotations

import collections
import contextlib
import threading
import weakref

from .base import MXNetError

_state = threading.local()

# telemetry hot-state (mxnet_tpu.profiler.core), installed by the first
# profiler.set_state('run'); None until then so unprofiled sessions pay a
# single `is None` test per site (see ops/registry.py)
_PROF = None

# fault-injection hot-state (resilience.faults.FaultPlan slot): None until
# a plan installs; wait points consult it so simulated async device errors
# surface exactly where contract (c) says real ones do
_FAULTS = None

# recently dispatched arrays (weakrefs): wait_all() drains these instead of
# blocking on every live array in the process (jax.live_arrays() is O(all
# arrays ever alive) — pathological when waitall() runs once per epoch).
# Tracking is per-thread (GIL-safe deque appends, no lock on the hot eager
# dispatch path); the registry of thread deques is what wait_all sweeps.
_PENDING_MAX = 4096
_pending_tls = threading.local()
_pending_registry = {}          # thread ident -> (thread weakref, deque)
_pending_orphans = collections.deque(maxlen=_PENDING_MAX)
_pending_lock = threading.Lock()  # guards registry + orphans


def _my_pending():
    dq = getattr(_pending_tls, "dq", None)
    if dq is None:
        dq = collections.deque(maxlen=_PENDING_MAX)
        _pending_tls.dq = dq
        ident = threading.get_ident()
        with _pending_lock:
            old = _pending_registry.get(ident)
            if old is not None:
                # ident reuse after a thread died: keep its undrained refs
                _pending_orphans.extend(old[1])
            _pending_registry[ident] = (
                weakref.ref(threading.current_thread()), dq)
    return dq


def track_async(arrays):
    """Record op outputs as outstanding async work for wait_all."""
    dq = _my_pending()
    for a in arrays:
        try:
            dq.append(weakref.ref(a))
        except TypeError:
            pass
    prof = _PROF
    if prof is not None and prof.ENABLED:
        # async queue depth gauge: outstanding dispatches on this thread
        prof.set_counter("engine.queue_depth", len(dq), cat="engine")


def engine_type() -> str:
    t = getattr(_state, "engine_type", None)
    if t is None:
        from . import config

        t = config.get("MXNET_ENGINE_TYPE")
        _state.engine_type = t
    return t


def set_engine_type(name: str):
    """'NaiveEngine' => synchronous op dispatch (debug aid)."""
    _state.engine_type = name


def is_naive() -> bool:
    return engine_type() == "NaiveEngine"


def maybe_sync(arrays):
    """Called by the dispatch layer after each op: tracks outputs for
    wait_all, and blocks immediately when NaiveEngine is on."""
    if is_naive():
        # already synced — nothing outstanding to track
        for a in arrays:
            try:
                a.block_until_ready()
            except AttributeError:
                pass
        return
    track_async(arrays)


def wait_for_var(data):
    """``Engine::WaitForVar`` analog: block until ``data`` is computed.
    The stall duration is recorded while the profiler runs."""
    prof = _PROF
    if prof is None or not prof.ENABLED:
        try:
            return data.block_until_ready()
        except AttributeError:
            return data
    t0 = prof.begin()
    try:
        try:
            return data.block_until_ready()
        except AttributeError:
            return data
    finally:
        prof.record_duration("engine::wait_for_var", "engine", t0)


def _block_settled(a):
    """Block on one tracked array. Returns ``'ok'``, ``'skip'``, or the
    failure exception. Donated-away buffers (fused optimizer /
    static_alloc donate arrays that were tracked as op outputs — blocking
    on one raises 'Array has been deleted', including the race where the
    delete lands after the ``is_deleted`` check) and non-waitable strays
    are skips, not failures."""
    try:
        is_deleted = getattr(a, "is_deleted", None)
        if is_deleted is not None and is_deleted():
            return "skip"
        a.block_until_ready()
        return "ok"
    except AttributeError:
        return "skip"  # no block_until_ready: not async work
    except Exception as e:
        if "deleted" in str(e).lower():
            return "skip"
        return e


def wait_all():
    """``MXNDArrayWaitAll`` analog: drain outstanding async work.

    Blocks on the recently-dispatched set (bounded deque of weakrefs) —
    O(recent ops), not O(live arrays). ``MXNET_WAITALL_FULL=1`` restores
    the exhaustive ``jax.live_arrays()`` sweep for debugging.

    Contract (c) of the module docstring: async device errors re-raise at
    wait points. The FIRST failure encountered while draining is kept and
    re-raised as ``MXNetError`` after the drain completes — every other
    outstanding array is still waited on first, so one poisoned dispatch
    doesn't leave the rest of the queue untracked for the next wait_all.
    """
    import jax

    from . import config

    prof = _PROF
    t0 = prof.begin() if prof is not None and prof.ENABLED else 0
    drained = 0
    first_failure = None
    flt = _FAULTS
    if flt is not None:
        flt.check("engine:wait")
    try:
        jax.effects_barrier()
    except AttributeError:
        pass  # jax version without effects_barrier
    except Exception as e:
        first_failure = e
    if config.get("MXNET_WAITALL_FULL"):
        try:
            live = jax.live_arrays()
        except Exception:
            live = []
        for a in live:
            r = _block_settled(a)
            if r == "ok":
                drained += 1
            elif r != "skip" and first_failure is None:
                first_failure = r
        if t0:
            prof.record_duration("engine::wait_all", "engine", t0,
                                 args={"mode": "full",
                                       "failed": first_failure is not None})
    else:
        with _pending_lock:
            deques = [dq for _, dq in _pending_registry.values()]
            deques.append(_pending_orphans)
            # prune registry entries for dead threads (their deques were
            # just captured above and get drained below) — no per-thread
            # leak
            dead = []
            for ident, (tref, _dq) in _pending_registry.items():
                t = tref()  # bind once: the second deref could race GC
                if t is None or not t.is_alive():
                    dead.append(ident)
            for ident in dead:
                del _pending_registry[ident]
        for dq in deques:
            while True:
                try:
                    ref = dq.popleft()
                except IndexError:
                    break
                a = ref()
                if a is None:
                    continue
                r = _block_settled(a)
                if r == "ok":
                    drained += 1
                elif r != "skip" and first_failure is None:
                    first_failure = r
        if t0:
            prof.record_duration("engine::wait_all", "engine", t0,
                                 args={"drained": drained,
                                       "failed": first_failure is not None})
            prof.set_counter("engine.queue_depth", 0, cat="engine")
    if first_failure is not None:
        raise MXNetError(
            f"async operation failed, surfaced at wait_all: "
            f"{type(first_failure).__name__}: {first_failure}"
        ) from first_failure


_BULK_SIZE = 15


def set_bulk_size(size):
    """Set the bulk-execution size limit (reference
    ``python/mxnet/engine.py:25``); returns the previous value. Advisory
    here: XLA fuses ops inside a trace, and the per-step analog of bulk
    execution is ``ShardedTrainer.step_n`` windows — the setting is kept
    for API parity and surfaced via :func:`bulk`."""
    global _BULK_SIZE
    prev = _BULK_SIZE
    _BULK_SIZE = int(size)
    return prev


@contextlib.contextmanager
def bulk(size: int = 15):
    """Bulk-execution scope (``engine.h:311-317``). Advisory: XLA fuses.
    The scope duration and flush size are recorded while profiling."""
    prev = set_bulk_size(size)
    prof = _PROF
    t0 = prof.begin() if prof is not None and prof.ENABLED else 0
    try:
        yield
    finally:
        set_bulk_size(prev)
        if t0:
            prof.record_duration("engine::bulk", "engine", t0,
                                 args={"size": size})


# ---------------------------------------------------------------------------
# Raw engine push API parity (``MXEnginePushAsync/Sync``, c_api.h:3028-3110).
# External schedulers in the reference can push closures with explicit var
# deps. Here ordering is data-flow exact, so push == call.
# ---------------------------------------------------------------------------


def push_sync(fn, *args, **kwargs):
    return fn(*args, **kwargs)


def push_async(fn, *args, on_complete=None, **kwargs):
    out = fn(*args, **kwargs)
    if on_complete is not None:
        on_complete()
    return out
