"""Native runtime components (C++, ctypes-bound).

The reference implements its IO/runtime hot paths in C++ (``src/io/``,
``src/storage/``...); this package holds the TPU build's equivalents. Each
component compiles on first use with g++ (no pip/cmake dependency at
install time) and caches the .so next to the sources; set
``MXNET_TPU_NO_NATIVE=1`` to force the pure-Python fallbacks.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_build_lock = threading.Lock()
_libs = {}


def _native_dir():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native")


def native_disabled():
    return os.environ.get("MXNET_TPU_NO_NATIVE", "0") == "1"


def load(name, source, extra_flags=()):
    """Compile (once) and dlopen native/<source> as lib<name>.so."""
    if native_disabled():
        return None
    with _build_lock:
        if name in _libs:
            return _libs[name]
        src = os.path.join(_native_dir(), source)
        if not os.path.exists(src):
            _libs[name] = None
            return None
        so = os.path.join(_native_dir(), f"lib{name}.so")
        if not os.path.exists(so) or (os.path.getmtime(so)
                                      < os.path.getmtime(src)):
            cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                   "-o", so, src, "-lpthread", *extra_flags]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
            except (subprocess.CalledProcessError, FileNotFoundError,
                    subprocess.TimeoutExpired):
                _libs[name] = None
                return None
        try:
            _libs[name] = ctypes.CDLL(so)
        except OSError:
            _libs[name] = None
        return _libs[name]


def recordio_lib():
    """The native recordio scanner/reader (see ``native/recordio.cc``)."""
    lib = load("recordio", "recordio.cc")
    if lib is not None and not getattr(lib, "_sigs_set", False):
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.rio_build_index.restype = ctypes.c_long
        lib.rio_build_index.argtypes = [ctypes.c_char_p, i64p, i64p,
                                        ctypes.c_long]
        lib.rio_read_at.restype = ctypes.c_long
        lib.rio_read_at.argtypes = [ctypes.c_char_p, ctypes.c_int64, u8p,
                                    ctypes.c_long]
        lib.rio_read_batch.restype = ctypes.c_long
        lib.rio_read_batch.argtypes = [ctypes.c_char_p, i64p, ctypes.c_long,
                                       u8p, ctypes.c_long, i64p]
        lib.rio_prefetch_open.restype = ctypes.c_void_p
        lib.rio_prefetch_open.argtypes = [ctypes.c_char_p, ctypes.c_long]
        lib.rio_prefetch_next.restype = ctypes.c_long
        lib.rio_prefetch_next.argtypes = [ctypes.c_void_p, u8p,
                                          ctypes.c_long]
        lib.rio_prefetch_close.restype = None
        lib.rio_prefetch_close.argtypes = [ctypes.c_void_p]
        lib._sigs_set = True
    return lib


def textparse_lib():
    """Native CSV/LibSVM parser (see ``native/textparse.cc``)."""
    lib = load("textparse", "textparse.cc")
    if lib is not None and not getattr(lib, "_sigs_set2", False):
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.txt_count_rows.restype = ctypes.c_long
        lib.txt_count_rows.argtypes = [ctypes.c_char_p]
        lib.csv_ncols.restype = ctypes.c_long
        lib.csv_ncols.argtypes = [ctypes.c_char_p]
        lib.csv_parse.restype = ctypes.c_long
        lib.csv_parse.argtypes = [ctypes.c_char_p, f32p, ctypes.c_long,
                                  ctypes.c_long]
        lib.libsvm_parse.restype = ctypes.c_long
        lib.libsvm_parse.argtypes = [ctypes.c_char_p, f32p, f32p,
                                     ctypes.c_long, ctypes.c_long]
        lib._sigs_set2 = True
    return lib
