"""Python surface over the native recordio library (``native/recordio.cc``).

Used by :mod:`mxnet_tpu.recordio` for index rebuilds and by sequential
pipelines for background prefetch; everything degrades to the pure-Python
implementation when the toolchain is unavailable.
"""
from __future__ import annotations

import ctypes

import numpy as _onp

from ..base import MXNetError
from . import recordio_lib


def available():
    return recordio_lib() is not None


def build_index(path):
    """Scan a .rec file, returning (offsets, sizes) int64 arrays."""
    lib = recordio_lib()
    if lib is None:
        raise MXNetError("native recordio unavailable (no g++?)")
    count = lib.rio_build_index(path.encode(), None, None, 0)
    if count < 0:
        raise MXNetError(f"corrupt recordio file {path}")
    offsets = _onp.zeros(count, dtype=_onp.int64)
    sizes = _onp.zeros(count, dtype=_onp.int64)
    got = lib.rio_build_index(
        path.encode(),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), count)
    if got != count:
        raise MXNetError(f"recordio file {path} changed during scan")
    return offsets, sizes


def read_at(path, offset, size_hint=1 << 16):
    """Read one logical record's payload."""
    lib = recordio_lib()
    if lib is None:
        raise MXNetError("native recordio unavailable")
    buf = (ctypes.c_uint8 * size_hint)()
    n = lib.rio_read_at(path.encode(), offset, buf, size_hint)
    if n < 0:
        raise MXNetError(f"read failed at {offset} in {path}")
    if n > size_hint:
        buf = (ctypes.c_uint8 * n)()
        n = lib.rio_read_at(path.encode(), offset, buf, n)
        if n < 0:
            raise MXNetError(f"read failed at {offset} in {path}")
    return bytes(bytearray(buf)[:n])


def read_batch(path, offsets, sizes=None):
    """Read many records in one native call; returns list of bytes."""
    lib = recordio_lib()
    if lib is None:
        raise MXNetError("native recordio unavailable")
    offs = _onp.ascontiguousarray(offsets, dtype=_onp.int64)
    n_rec = len(offs)
    cap = int(sizes.sum()) if sizes is not None else (1 << 20) * n_rec
    buf = (ctypes.c_uint8 * cap)()
    lengths = _onp.zeros(n_rec, dtype=_onp.int64)
    used = lib.rio_read_batch(
        path.encode(), offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n_rec, buf, cap, lengths.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)))
    if used < 0:
        # retry with exact sizes from the probe
        return read_batch(path, offs, sizes=lengths)
    raw = bytes(bytearray(buf)[:used])
    out = []
    pos = 0
    for l in lengths:
        out.append(raw[pos:pos + int(l)])
        pos += int(l)
    return out


class NativePrefetchReader:
    """Sequential reader with a C++ background thread filling a bounded
    queue (reference ``src/io/iter_prefetcher.h``)."""

    def __init__(self, path, queue_depth=16, max_record=1 << 24):
        lib = recordio_lib()
        if lib is None:
            raise MXNetError("native recordio unavailable")
        self._lib = lib
        self._handle = lib.rio_prefetch_open(path.encode(), queue_depth)
        if not self._handle:
            raise MXNetError(f"cannot open {path}")
        self._buf = (ctypes.c_uint8 * max_record)()
        self._max = max_record

    def __iter__(self):
        return self

    def __next__(self):
        n = self._lib.rio_prefetch_next(self._handle, self._buf, self._max)
        if n == 0:
            raise StopIteration
        if n < 0:
            raise MXNetError("record exceeds max_record buffer")
        return bytes(bytearray(self._buf)[:n])

    def close(self):
        if self._handle:
            self._lib.rio_prefetch_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # pylint: disable=broad-except
            pass
