"""Python surface over the native text parsers (``native/textparse.cc``).

The reference parses CSV/LibSVM in threaded C++ iterators
(``src/io/iter_csv.cc:218``, ``src/io/iter_libsvm.cc:200``); this module
exposes that tier. Falls back to None when the toolchain is unavailable —
callers then use numpy.loadtxt-style paths.
"""
from __future__ import annotations

import ctypes

import numpy as _onp

from ..base import MXNetError
from . import textparse_lib


def available():
    return textparse_lib() is not None


def load_csv(path) -> _onp.ndarray:
    """Parse a uniform-width float CSV into a (rows, cols) float32 array."""
    lib = textparse_lib()
    if lib is None:
        raise MXNetError("native textparse unavailable (no g++?)")
    path_b = str(path).encode()
    rows = lib.txt_count_rows(path_b)
    cols = lib.csv_ncols(path_b)
    if rows < 0 or cols < 0:
        raise MXNetError(f"cannot read {path}")
    out = _onp.empty((rows, cols), dtype=_onp.float32)
    n = lib.csv_parse(path_b,
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      out.size, cols)
    if n < 0:
        raise MXNetError(f"malformed CSV {path} (ragged rows or bad float)")
    return out[:n // cols]


def load_libsvm(path, num_features) -> tuple:
    """Parse LibSVM into dense (rows, num_features) float32 + (rows,)
    labels (the reference iterator's dense storage fallback)."""
    lib = textparse_lib()
    if lib is None:
        raise MXNetError("native textparse unavailable (no g++?)")
    path_b = str(path).encode()
    rows = lib.txt_count_rows(path_b)
    if rows < 0:
        raise MXNetError(f"cannot read {path}")
    data = _onp.zeros((rows, num_features), dtype=_onp.float32)
    label = _onp.zeros((rows,), dtype=_onp.float32)
    n = lib.libsvm_parse(
        path_b, data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows, num_features)
    if n < 0:
        raise MXNetError(f"malformed LibSVM file {path}")
    return data[:n], label[:n]
