"""Optimizers (reference ``python/mxnet/optimizer/`` — 18 classes, fused C++
kernels in ``src/operator/optimizer_op*.cc`` / ``contrib/{adamw,lamb}``).

TPU design: every optimizer defines a *pure* update rule
``_update_raw(p, g, states, lr, wd, t) -> (new_p, new_states)`` on jax
arrays. The eager ``update()`` API applies it per-parameter (MXNet
semantics); ``gluon.Trainer`` compiles ONE jitted multi-tensor update over
all parameters with buffer donation — the role of the reference's
multi-tensor/aggregate update kernels (``aggregate_num`` batching).
"""
from __future__ import annotations

import math

import numpy as _onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

_OPT_REGISTRY = {}


def register(cls):
    _OPT_REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    try:
        return _OPT_REGISTRY[name.lower()](**kwargs)
    except KeyError:
        raise MXNetError(f"unknown optimizer {name!r}") from None


class Optimizer:
    """Base optimizer."""

    # False for optimizers with python-side state or per-step host RNG that
    # cannot be baked into one compiled multi-tensor update (Trainer falls
    # back to the reference's eager per-parameter path).
    fused_safe = True

    # False for optimizers whose update depends on whole-tensor reductions
    # (layer-wise norms): concatenating several params into one flat
    # fusion buffer (kvstore.bucketing) would change their math, so the
    # ZeRO bucketed path refuses them.
    elementwise = True

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 multi_precision=False, param_dict=None, aggregate_num=0,
                 use_fused_step=True, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        self.use_fused_step = use_fused_step
        self.param_dict = param_dict or {}
        self.idx2name = param_idx2name or {}
        self._index_update_count = {}
        self._all_kwargs = dict(kwargs)

    # -- bookkeeping ------------------------------------------------------
    def _update_count(self, index):
        self._index_update_count[index] = self._index_update_count.get(index, 0) + 1
        return self._index_update_count[index]

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            t = max(self._index_update_count.values(), default=0)
            return self.lr_scheduler(t)
        return self.lr

    @learning_rate.setter
    def learning_rate(self, lr):
        self.lr = lr

    def set_learning_rate(self, lr):
        self.lr = lr

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self._index_update_count.get(index, 0))
        else:
            lr = self.lr
        p = self.param_dict.get(index)
        if p is not None:
            lr *= getattr(p, "lr_mult", 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        p = self.param_dict.get(index)
        if p is not None:
            wd *= getattr(p, "wd_mult", 1.0)
        return wd

    # -- state ------------------------------------------------------------
    def create_state(self, index, weight):  # pylint: disable=unused-argument
        return ()

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == _onp.float16:
            master = NDArray(weight._data.astype(_onp.float32))
            return (master, self.create_state(index, NDArray(master._data)))
        return self.create_state(index, weight)

    # -- pure rule (jax arrays) -------------------------------------------
    def _update_raw(self, p, g, states, lr, wd, t):
        raise NotImplementedError

    def _prep_grad(self, g):
        import jax.numpy as jnp

        g = g * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    # -- eager per-param API (MXNet semantics) ----------------------------
    def update(self, index, weight, grad, state):
        if isinstance(index, (list, tuple)):
            for i, w, g, s in zip(index, weight, grad, state):
                self._update_one(i, w, g, s)
        else:
            self._update_one(index, weight, grad, state)

    def _update_one(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(grad, RowSparseNDArray) \
                and getattr(self, "lazy_update", False):
            return self._update_one_lazy(index, weight, grad, state)
        t = self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = self._prep_grad(grad._data.astype(weight.dtype))
        states = _states_tuple(state)
        sdatas = tuple(s._data for s in states)
        new_p, new_s = self._update_raw(weight._data, g, sdatas, lr, wd, t)
        weight._set_data_internal(new_p)
        for s, ns in zip(states, new_s):
            s._set_data_internal(ns)

    def _update_one_lazy(self, index, weight, grad, state):
        """Row-sparse lazy update: gather the touched rows of weight and
        state, run the SAME ``_update_raw`` rule on just those rows, and
        scatter back — O(nnz·cols) FLOPs regardless of vocab size. This is
        the reference's ``lazy_update`` contract
        (``src/operator/optimizer_op.cc`` SGD/Adam row_sparse kernels):
        momentum/wd are applied ONLY to rows present in the gradient."""
        t = self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        rows = grad.indices._data
        g = self._prep_grad(grad.values._data.astype(weight.dtype))
        pd = weight._data
        states = _states_tuple(state)
        new_p_rows, new_s_rows = self._update_raw(
            pd[rows], g, tuple(s._data[rows] for s in states), lr, wd, t)
        weight._set_data_internal(pd.at[rows].set(new_p_rows))
        for s, ns in zip(states, new_s_rows):
            s._set_data_internal(s._data.at[rows].set(ns))

    def update_multi_precision(self, index, weight, grad, state):
        if (self.multi_precision and isinstance(state, tuple) and len(state) == 2
                and isinstance(state[0], NDArray)
                and state[0].dtype == _onp.float32
                and weight.dtype == _onp.float16):
            master, inner = state
            t = self._update_count(index)
            lr = self._get_lr(index)
            wd = self._get_wd(index)
            g = self._prep_grad(grad._data.astype(_onp.float32))
            states = _states_tuple(inner)
            sdatas = tuple(s._data for s in states)
            new_p, new_s = self._update_raw(master._data, g, sdatas, lr, wd, t)
            master._set_data_internal(new_p)
            for s, ns in zip(states, new_s):
                s._set_data_internal(ns)
            weight._set_data_internal(new_p.astype(_onp.float16))
        else:
            self.update(index, weight, grad, state)

    def __repr__(self):
        return f"{type(self).__name__}(lr={self.lr})"


def _states_tuple(state):
    if state is None:
        return ()
    if isinstance(state, NDArray):
        return (state,)
    return tuple(state)


def _zeros_like(weight):
    import jax.numpy as jnp

    return NDArray(jnp.zeros(weight.shape, weight.dtype))


@register
class SGD(Optimizer):
    """SGD with momentum (reference ``optimizer/sgd.py``)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=False, **kwargs):
        # lazy_update defaults False, matching the reference 2.x
        # (python/mxnet/optimizer/sgd.py:95): opted in, row_sparse grads
        # update only their stored rows — skipping momentum decay and wd
        # on untouched rows, a documented numerics divergence from the
        # dense update
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        if lazy_update and kwargs.get("multi_precision"):
            # reference sgd.py:105-107 forbids the combination: the fp32
            # master copy would drift from the lazily-updated weight
            raise ValueError("lazy_update is not compatible with "
                             "multi_precision (reference sgd.py:105)")
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (_zeros_like(weight),)

    def _update_raw(self, p, g, states, lr, wd, t):
        g = g + wd * p
        if self.momentum == 0.0:
            return p - lr * g, ()
        (mom,) = states
        mom = self.momentum * mom - lr * g
        return p + mom, (mom,)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD."""

    def __init__(self, learning_rate=0.1, momentum=0.9, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return (_zeros_like(weight),)

    def _update_raw(self, p, g, states, lr, wd, t):
        g = g + wd * p
        (mom,) = states
        mom = self.momentum * mom + g
        return p - lr * (g + self.momentum * mom), (mom,)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, correct_bias=True, lazy_update=False,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.correct_bias = correct_bias
        # opt-in (reference adam.py): row_sparse grads touch only stored
        # rows — moment decay is skipped for absent rows
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        g = g + wd * p
        m, v = states
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        if self.correct_bias:
            mhat = m / (1 - self.beta1 ** t)
            vhat = v / (1 - self.beta2 ** t)
        else:
            mhat, vhat = m, v
        return p - lr * mhat / (jnp.sqrt(vhat) + self.epsilon), (m, v)


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay (reference ``contrib/adamw``)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, correct_bias=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.correct_bias = correct_bias

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        m, v = states
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        if self.correct_bias:
            mhat = m / (1 - self.beta1 ** t)
            vhat = v / (1 - self.beta2 ** t)
        else:
            mhat, vhat = m, v
        return p - lr * (mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * p), (m, v)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.momentum, self.epsilon = rho, momentum, epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_zeros_like(weight), _zeros_like(weight), _zeros_like(weight))
        return (_zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        g = g + wd * p
        if self.centered:
            n, mg, mom = states
            n = self.rho * n + (1 - self.rho) * g * g
            mg = self.rho * mg + (1 - self.rho) * g
            mom = self.momentum * mom - lr * g / jnp.sqrt(n - mg * mg + self.epsilon)
            p = p + mom
            if self.clip_weights:
                p = jnp.clip(p, -self.clip_weights, self.clip_weights)
            return p, (n, mg, mom)
        n, mom = states
        n = self.rho * n + (1 - self.rho) * g * g
        mom = self.momentum * mom - lr * g / (jnp.sqrt(n) + self.epsilon)
        p = p + mom
        if self.clip_weights:
            p = jnp.clip(p, -self.clip_weights, self.clip_weights)
        return p, (n, mom)


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=0.01, epsilon=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight),)

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        g = g + wd * p
        (h,) = states
        h = h + g * g
        return p - lr * g / (jnp.sqrt(h) + self.epsilon), (h,)


@register
class AdaDelta(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        g = g + wd * p
        acc_g, acc_d = states
        acc_g = self.rho * acc_g + (1 - self.rho) * g * g
        delta = jnp.sqrt(acc_d + self.epsilon) / jnp.sqrt(acc_g + self.epsilon) * g
        acc_d = self.rho * acc_d + (1 - self.rho) * delta * delta
        return p - lr * delta, (acc_g, acc_d)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        g = g + wd * p
        m, u = states
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        lr_t = lr / (1 - self.beta1 ** t)
        return p - lr_t * m / (u + 1e-8), (m, u)


@register
class Nadam(Optimizer):
    fused_safe = False  # python-side m_schedule state

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        g = g + wd * p
        m, v = states
        mt = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mt1 = self.beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * mt
        sched1 = self.m_schedule
        sched2 = self.m_schedule * mt1
        gp = g / (1 - sched1)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        mhat = m / (1 - sched2)
        vhat = v / (1 - self.beta2 ** t)
        mbar = (1 - mt) * gp + mt1 * mhat
        return p - lr * mbar / (jnp.sqrt(vhat) + self.epsilon), (m, v)


@register
class Ftrl(Optimizer):
    def __init__(self, learning_rate=0.1, lamda1=0.01, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        z, n = states
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
        z = z + g - sigma * p
        n = n + g * g
        p_new = jnp.where(
            jnp.abs(z) > self.lamda1,
            -(z - jnp.sign(z) * self.lamda1)
            / ((self.beta + jnp.sqrt(n)) / lr + wd),
            0.0,
        )
        return p_new, (z, n)


@register
class FTML(Optimizer):
    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        g = g + wd * p
        d, v, z = states
        v = self.beta2 * v + (1 - self.beta2) * g * g
        d_t = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(v / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * d
        z = self.beta1 * z + (1 - self.beta1) * g - sigma * p
        return -z / d_t, (d_t, v, z)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (_zeros_like(weight),)

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        if self.momentum == 0.0:
            return p * (1 - lr * self.wd_lh) - lr * jnp.sign(g + wd * p), ()
        (mom,) = states
        mom = self.momentum * mom - (1 - self.momentum) * (g + wd * p)
        return p * (1 - lr * self.wd_lh) + lr * jnp.sign(mom), (mom,)


SignSGD = Signum


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference ``optimizer/lars.py``)."""

    elementwise = False  # per-tensor norm ratio

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.eta, self.epsilon = momentum, eta, epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight),)

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        (mom,) = states
        w_norm = jnp.sqrt(jnp.sum(p * p))
        g_norm = jnp.sqrt(jnp.sum(g * g))
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon), 1.0)
        g = g + wd * p
        mom = self.momentum * mom + trust * lr * g
        return p - mom, (mom,)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments for batch training (reference lamb)."""

    elementwise = False  # per-tensor trust ratio

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        m, v = states
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        if self.bias_correction:
            mhat = m / (1 - self.beta1 ** t)
            vhat = v / (1 - self.beta2 ** t)
        else:
            mhat, vhat = m, v
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * p
        w_norm = jnp.sqrt(jnp.sum(p * p))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        if self.lower_bound is not None:
            w_norm = jnp.maximum(w_norm, self.lower_bound)
        if self.upper_bound is not None:
            w_norm = jnp.minimum(w_norm, self.upper_bound)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * ratio * r, (m, v)


@register
class LANS(Optimizer):
    """Accelerated large-batch (normalized gradients) variant of LAMB."""

    elementwise = False  # per-tensor grad normalization + trust ratio

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.numpy as jnp

        g_norm = jnp.sqrt(jnp.sum(g * g))
        g = jnp.where(g_norm > 0, g / g_norm, g)
        m, v = states
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        w_norm = jnp.sqrt(jnp.sum(p * p))
        r1 = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * p
        r1n = jnp.sqrt(jnp.sum(r1 * r1))
        ratio1 = jnp.where((w_norm > 0) & (r1n > 0), w_norm / r1n, 1.0)
        r2 = g / (jnp.sqrt(vhat) + self.epsilon) + wd * p
        r2n = jnp.sqrt(jnp.sum(r2 * r2))
        ratio2 = jnp.where((w_norm > 0) & (r2n > 0), w_norm / r2n, 1.0)
        p = p - lr * (self.beta1 * ratio1 * r1 + (1 - self.beta1) * ratio2 * r2)
        return p, (m, v)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (adds gaussian noise)."""

    fused_safe = False  # fresh RNG draw per step

    def __init__(self, learning_rate=0.01, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)

    def _update_raw(self, p, g, states, lr, wd, t):
        import jax.random as jr

        from .. import random as _rng

        g = g + wd * p
        noise = jr.normal(_rng.next_key(), p.shape, p.dtype) * math.sqrt(lr)
        return p - 0.5 * lr * g + noise, ()


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference dcasgd)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        return (_zeros_like(weight), NDArray(weight._data))

    def _update_raw(self, p, g, states, lr, wd, t):
        g = g + wd * p
        mom, prev_w = states
        mom = self.momentum * mom - lr * (
            g + self.lamda * g * g * (p - prev_w))
        return p + mom, (mom, p + mom)


# name aliases matching reference create() strings
_OPT_REGISTRY.update(
    sgd=SGD, nag=NAG, adam=Adam, adamw=AdamW, rmsprop=RMSProp,
    adagrad=AdaGrad, adadelta=AdaDelta, adamax=Adamax, nadam=Nadam,
    ftrl=Ftrl, ftml=FTML, signum=Signum, signsgd=Signum, lars=LARS,
    lamb=LAMB, lans=LANS, sgld=SGLD, dcasgd=DCASGD,
)


class Updater:
    """Applies an optimizer to (index, grad, weight) triples — the object
    serialized to KVStore servers in the reference (``updater.py``)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):  # pylint: disable=unused-argument
        import pickle

        def to_host(s):
            if s is None:
                return None
            if isinstance(s, NDArray):
                return s.asnumpy()
            return tuple(to_host(x) for x in s)

        return pickle.dumps({k: to_host(v) for k, v in self.states.items()})

    def set_states(self, states_blob):
        import pickle

        def to_dev(s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(to_dev(x) for x in s)
            return NDArray(s)

        loaded = pickle.loads(states_blob)
        for k, v in loaded.items():
            self.states[k] = to_dev(v)


def get_updater(optimizer):
    return Updater(optimizer)
