"""``mx.optimizer`` (reference ``python/mxnet/optimizer/``)."""
from __future__ import annotations

from .optimizer import (
    DCASGD,
    FTML,
    LAMB,
    LANS,
    LARS,
    NAG,
    SGD,
    SGLD,
    AdaDelta,
    AdaGrad,
    Adam,
    AdamW,
    Adamax,
    Ftrl,
    Nadam,
    Optimizer,
    RMSProp,
    SignSGD,
    Signum,
    Updater,
    create,
    get_updater,
    register,
)
